"""Gradient-communication strategies (parallel/collectives.py) on the
8-virtual-device CPU mesh.

The parity ladder the PR's acceptance pins:
  * pmean     — the baseline; two independent builds are BITWISE identical
    (the exact-DDP-semantics anchor).
  * sharded   — reduce-scatter + 1/N sharded SGD + all-gather; matches the
    pmean baseline to f32 reduction-order tolerance (rtol 1e-6) after 3
    steps.
  * bf16      — compressed allreduce; drift vs pmean is BOUNDED (the cast
    error of ~2^-8 relative on the gradient, times lr, per step) and the
    bound is pinned here.

Plus the supporting machinery: wire-byte accounting, bucketization
invariance, stochastic rounding, the comm probe, and the strategy-rejection
contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_ddp_mnist_tpu.compat import shard_map
from pytorch_ddp_mnist_tpu.models import init_mlp, param_count
from pytorch_ddp_mnist_tpu.parallel import collectives
from pytorch_ddp_mnist_tpu.parallel.ddp import (
    batch_sharding, make_dp_train_step, replicated)
from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= N_DEV
    return make_mesh([N_DEV], ["dp"], jax.devices()[:N_DEV])


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def _train(mesh, comm, steps=3, lr=0.05):
    step = make_dp_train_step(mesh, lr=lr, comm=comm)
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    x, y = _batch(N_DEV * 16, seed=3)
    for _ in range(steps):
        xs = jax.device_put(x, batch_sharding(mesh))
        ys = jax.device_put(y, batch_sharding(mesh))
        params, key, loss = step(params, key, xs, ys)
    assert np.isfinite(float(loss))
    return jax.tree_util.tree_map(np.asarray, params)


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def test_pmean_is_bitwise_deterministic(mesh):
    """Two independent builds of the pmean step produce bit-identical
    params — the exact-DDP-semantics anchor every other strategy is
    measured against."""
    a, b = _train(mesh, "pmean"), _train(mesh, "pmean")
    for u, v in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(u, v)


def test_sharded_matches_pmean_rtol_1e6(mesh):
    """The acceptance pin: 3 sharded-update steps match the pmean baseline
    to rtol 1e-6 (same mean gradient, different — but order-stable —
    reduction tree)."""
    ref, got = _train(mesh, "pmean"), _train(mesh, "sharded")
    for u, v in zip(_leaves(ref), _leaves(got)):
        np.testing.assert_allclose(v, u, rtol=1e-6, atol=1e-7)


def test_bf16_drift_bounded(mesh):
    """Compressed-allreduce drift after 3 steps stays within the analytic
    envelope: per step the bf16 cast perturbs the mean gradient by at most
    ~2^-8 relative, the param delta by lr * that — orders of magnitude
    below the 1e-4 pin here, which still fails instantly on a wrong-mean
    bug (that error would be O(grad) ~ 1e-2)."""
    ref, got = _train(mesh, "pmean"), _train(mesh, "bf16")
    worst = max(float(np.max(np.abs(u - v)))
                for u, v in zip(_leaves(ref), _leaves(got)))
    assert 0 < worst < 1e-4, worst


def test_bf16_stochastic_rounding_mode(mesh):
    """The `bf16_rounding='stochastic'` knob is live (the trajectory
    differs from the deterministic cast) and stays inside the same drift
    envelope vs pmean."""
    ref = _train(mesh, "pmean")
    det = _train(mesh, "bf16")

    def train_sr():
        step = make_dp_train_step(mesh, lr=0.05, comm="bf16",
                                  bf16_rounding="stochastic")
        params = jax.device_put(init_mlp(jax.random.key(0)),
                                replicated(mesh))
        key = jax.device_put(jax.random.key(1), replicated(mesh))
        x, y = _batch(N_DEV * 16, seed=3)
        for _ in range(3):
            params, key, loss = step(
                params, key,
                jax.device_put(x, batch_sharding(mesh)),
                jax.device_put(y, batch_sharding(mesh)))
        assert np.isfinite(float(loss))
        return jax.tree_util.tree_map(np.asarray, params)

    sr = train_sr()
    assert any(not np.array_equal(u, v)
               for u, v in zip(_leaves(sr), _leaves(det)))
    worst = max(float(np.max(np.abs(u - v)))
                for u, v in zip(_leaves(ref), _leaves(sr)))
    assert 0 < worst < 1e-4, worst


def test_bf16_rounding_rejected_off_bf16(mesh):
    with pytest.raises(ValueError, match="never casts"):
        make_dp_train_step(mesh, lr=0.01, comm="sharded",
                           bf16_rounding="stochastic")
    with pytest.raises(ValueError, match="nearest"):
        collectives.validate_bf16_rounding("truncate", "bf16")


def test_unknown_strategy_rejected_by_name(mesh):
    with pytest.raises(ValueError, match="fp8"):
        make_dp_train_step(mesh, lr=0.01, comm="fp8")
    with pytest.raises(ValueError, match="unknown DDP comm"):
        collectives.validate_comm("ring")


def test_bytes_on_wire_math():
    """Ring cost model, exact ints for the flagship 118,272-param MLP on
    8 devices (the docs/PERF.md §DDP table numbers)."""
    n = param_count(init_mlp(jax.random.key(0)))
    assert n == 118272
    ring = 7 / 8
    assert collectives.bytes_on_wire(n, 8, "pmean") == int(2 * ring * 4 * n)
    assert collectives.bytes_on_wire(n, 8, "bf16") == int(2 * ring * 2 * n)
    # sharded pads each bucket to a device multiple; the params pytree form
    # pads exactly (118272 already divides by 8 -> same as pmean here)
    params = init_mlp(jax.random.key(0))
    assert collectives.bytes_on_wire(params, 8, "sharded") == \
        int(2 * ring * 4 * collectives.padded_size(n, 8))
    # 1 device communicates nothing, whatever the strategy
    for comm in collectives.STRATEGIES:
        assert collectives.bytes_on_wire(n, 1, comm) == 0


def test_padded_size():
    assert collectives.padded_size(16, 8) == 16
    assert collectives.padded_size(17, 8) == 24
    assert collectives.padded_size(1, 8) == 8


def test_sharded_update_bucketization_invariant(mesh):
    """Forcing multi-bucket flattening (tiny bucket budget) produces the
    same update as the single-bucket default — the bucket boundaries are
    pure layout."""
    params = init_mlp(jax.random.key(0))
    grads = jax.tree_util.tree_map(
        lambda a: jnp.full_like(a, 0.25), params)

    def run(bucket_elems):
        f = shard_map(
            lambda p, g: collectives.sharded_update(
                p, g, 0.1, "dp", N_DEV, bucket_elems=bucket_elems),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
        return jax.tree_util.tree_map(np.asarray, jax.jit(f)(params, grads))

    small = run(1000)   # forces several buckets incl. a padded ragged one
    big = run(collectives.DEFAULT_BUCKET_ELEMS)
    for u, v in zip(_leaves(small), _leaves(big)):
        np.testing.assert_allclose(u, v, rtol=1e-7)
    # and the math is the plain SGD step: p - lr*g exactly (grads equal on
    # every device, so the scattered mean is the input gradient)
    for u, p0 in zip(_leaves(small), _leaves(params)):
        np.testing.assert_allclose(u, np.asarray(p0) - 0.1 * 0.25, rtol=1e-6)


def test_stochastic_round_bf16_neighbors_and_bias():
    """Stochastic rounding lands on one of the two enclosing bf16 values
    and its mean over keys tracks the f32 input more closely than the
    deterministic round-to-nearest cast."""
    x = jnp.linspace(0.001, 1.0, 1024, dtype=jnp.float32)
    keys = jax.random.split(jax.random.key(7), 128)
    rounded = jax.vmap(
        lambda k: collectives.stochastic_round_bf16(k, x))(keys)
    r32 = np.asarray(rounded.astype(jnp.float32))
    xn = np.asarray(x)
    # neighbors: every draw is the truncation or its bf16 successor
    lo = np.asarray(
        jax.lax.bitcast_convert_type(
            jax.lax.bitcast_convert_type(x, jnp.uint32)
            & jnp.uint32(0xFFFF0000), jnp.float32))
    hi = np.asarray(
        jax.lax.bitcast_convert_type(
            (jax.lax.bitcast_convert_type(x, jnp.uint32)
             & jnp.uint32(0xFFFF0000)) + jnp.uint32(0x10000), jnp.float32))
    assert np.all((r32 == lo[None]) | (r32 == hi[None]))
    stoch_bias = np.abs(r32.mean(axis=0) - xn).max()
    det_bias = np.abs(
        np.asarray(x.astype(jnp.bfloat16).astype(jnp.float32)) - xn).max()
    assert stoch_bias < det_bias


def test_comm_probe_runs_every_strategy(mesh):
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    for comm in collectives.STRATEGIES:
        probe = collectives.make_comm_probe(mesh, comm)
        secs = collectives.measure_collective_seconds(probe, params, reps=2)
        assert len(secs) == 2 and all(s > 0 for s in secs)


def test_dp_run_fn_comm_matches_step_loop(mesh):
    """The epoch-scanned DP program with comm='sharded' stays allclose to
    its comm='pmean' twin — the scan layer threads the strategy through
    _dp_step_body identically to the streaming step."""
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    from jax.sharding import NamedSharding

    n_rows = N_DEV * 64
    rng = np.random.default_rng(0)
    x_all = rng.normal(size=(n_rows, 784)).astype(np.float32)
    y_all = rng.integers(0, 10, size=n_rows).astype(np.int32)
    idxs = np.arange(n_rows, dtype=np.int32).reshape(1, 4, N_DEV * 16)

    def run(comm):
        fn = make_dp_run_fn(mesh, lr=0.05, comm=comm)
        rep = replicated(mesh)
        p = jax.device_put(init_mlp(jax.random.key(0)), rep)
        k = jax.device_put(jax.random.key(1), rep)
        out = fn(p, k,
                 jax.device_put(x_all, rep), jax.device_put(y_all, rep),
                 jax.device_put(idxs, NamedSharding(mesh, P(None, None,
                                                            "dp"))))
        return (jax.tree_util.tree_map(np.asarray, out[0]),
                np.asarray(out[2]))

    p_ref, l_ref = run("pmean")
    p_sh, l_sh = run("sharded")
    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-6, atol=1e-7)
    for u, v in zip(_leaves(p_ref), _leaves(p_sh)):
        np.testing.assert_allclose(v, u, rtol=1e-6, atol=1e-7)


def test_pallas_epoch_rejects_comm(mesh):
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    with pytest.raises(ValueError, match="IN-kernel"):
        make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch", comm="sharded")


def test_ddp_comm_recorder_publishes_metrics(mesh):
    """The train-loop recorder lands ddp.bytes_on_wire in the process
    registry even with telemetry disabled (counter = cheap host math), and
    the probe histogram only when a tracer is live."""
    from pytorch_ddp_mnist_tpu.telemetry import get_registry
    from pytorch_ddp_mnist_tpu.train.loop import make_ddp_comm_recorder

    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    rec = make_ddp_comm_recorder(mesh, "sharded", N_DEV, params)
    reg = get_registry()
    before = reg.counter("ddp.bytes_on_wire").value
    h_before = reg.histogram("ddp.collective_s").n
    rec(10, params)
    per_step = collectives.bytes_on_wire(params, N_DEV, "sharded")
    assert reg.counter("ddp.bytes_on_wire").value == before + 10 * per_step
    # telemetry disabled (NullTracer): no probe reps were recorded
    assert reg.histogram("ddp.collective_s").n == h_before
