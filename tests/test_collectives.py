"""Gradient-communication strategies (parallel/collectives.py) on the
8-virtual-device CPU mesh.

The parity ladder the PR's acceptance pins:
  * pmean     — the baseline; two independent builds are BITWISE identical
    (the exact-DDP-semantics anchor).
  * sharded   — reduce-scatter + 1/N sharded SGD + all-gather; matches the
    pmean baseline to f32 reduction-order tolerance (rtol 1e-6) after 3
    steps.
  * bf16      — compressed allreduce; drift vs pmean is BOUNDED (the cast
    error of ~2^-8 relative on the gradient, times lr, per step) and the
    bound is pinned here.

Plus the supporting machinery: wire-byte accounting, bucketization
invariance, stochastic rounding, the comm probe, and the strategy-rejection
contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_ddp_mnist_tpu.compat import shard_map
from pytorch_ddp_mnist_tpu.models import init_mlp, param_count
from pytorch_ddp_mnist_tpu.parallel import collectives
from pytorch_ddp_mnist_tpu.parallel.ddp import (
    batch_sharding, make_dp_train_step, replicated)
from pytorch_ddp_mnist_tpu.parallel.mesh import make_mesh

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= N_DEV
    return make_mesh([N_DEV], ["dp"], jax.devices()[:N_DEV])


def _batch(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def _train(mesh, comm, steps=3, lr=0.05):
    step = make_dp_train_step(mesh, lr=lr, comm=comm)
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    resid = step.place_comm_state(None, params) if step.comm_state else None
    x, y = _batch(N_DEV * 16, seed=3)
    for _ in range(steps):
        xs = jax.device_put(x, batch_sharding(mesh))
        ys = jax.device_put(y, batch_sharding(mesh))
        if step.comm_state:
            params, key, loss, resid = step(params, key, xs, ys, resid)
        else:
            params, key, loss = step(params, key, xs, ys)
    assert np.isfinite(float(loss))
    return jax.tree_util.tree_map(np.asarray, params)


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def test_pmean_is_bitwise_deterministic(mesh):
    """Two independent builds of the pmean step produce bit-identical
    params — the exact-DDP-semantics anchor every other strategy is
    measured against."""
    a, b = _train(mesh, "pmean"), _train(mesh, "pmean")
    for u, v in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(u, v)


def test_sharded_matches_pmean_rtol_1e6(mesh):
    """The acceptance pin: 3 sharded-update steps match the pmean baseline
    to rtol 1e-6 (same mean gradient, different — but order-stable —
    reduction tree)."""
    ref, got = _train(mesh, "pmean"), _train(mesh, "sharded")
    for u, v in zip(_leaves(ref), _leaves(got)):
        np.testing.assert_allclose(v, u, rtol=1e-6, atol=1e-7)


def test_bf16_drift_bounded(mesh):
    """Compressed-allreduce drift after 3 steps stays within the analytic
    envelope: per step the bf16 cast perturbs the mean gradient by at most
    ~2^-8 relative, the param delta by lr * that — orders of magnitude
    below the 1e-4 pin here, which still fails instantly on a wrong-mean
    bug (that error would be O(grad) ~ 1e-2)."""
    ref, got = _train(mesh, "pmean"), _train(mesh, "bf16")
    worst = max(float(np.max(np.abs(u - v)))
                for u, v in zip(_leaves(ref), _leaves(got)))
    assert 0 < worst < 1e-4, worst


def test_bf16_stochastic_rounding_mode(mesh):
    """The `bf16_rounding='stochastic'` knob is live (the trajectory
    differs from the deterministic cast) and stays inside the same drift
    envelope vs pmean."""
    ref = _train(mesh, "pmean")
    det = _train(mesh, "bf16")

    def train_sr():
        step = make_dp_train_step(mesh, lr=0.05, comm="bf16",
                                  bf16_rounding="stochastic")
        params = jax.device_put(init_mlp(jax.random.key(0)),
                                replicated(mesh))
        key = jax.device_put(jax.random.key(1), replicated(mesh))
        x, y = _batch(N_DEV * 16, seed=3)
        for _ in range(3):
            params, key, loss = step(
                params, key,
                jax.device_put(x, batch_sharding(mesh)),
                jax.device_put(y, batch_sharding(mesh)))
        assert np.isfinite(float(loss))
        return jax.tree_util.tree_map(np.asarray, params)

    sr = train_sr()
    assert any(not np.array_equal(u, v)
               for u, v in zip(_leaves(sr), _leaves(det)))
    worst = max(float(np.max(np.abs(u - v)))
                for u, v in zip(_leaves(ref), _leaves(sr)))
    assert 0 < worst < 1e-4, worst


def test_bf16_rounding_rejected_off_bf16(mesh):
    with pytest.raises(ValueError, match="never casts"):
        make_dp_train_step(mesh, lr=0.01, comm="sharded",
                           bf16_rounding="stochastic")
    with pytest.raises(ValueError, match="nearest"):
        collectives.validate_bf16_rounding("truncate", "bf16")


def test_unknown_strategy_rejected_by_name(mesh):
    with pytest.raises(ValueError, match="fp8"):
        make_dp_train_step(mesh, lr=0.01, comm="fp8")
    with pytest.raises(ValueError, match="unknown DDP comm"):
        collectives.validate_comm("ring")


def test_bytes_on_wire_math():
    """Ring cost model, exact ints for the flagship 118,272-param MLP on
    8 devices (the docs/PERF.md §DDP table numbers)."""
    n = param_count(init_mlp(jax.random.key(0)))
    assert n == 118272
    ring = 7 / 8
    assert collectives.bytes_on_wire(n, 8, "pmean") == int(2 * ring * 4 * n)
    assert collectives.bytes_on_wire(n, 8, "bf16") == int(2 * ring * 2 * n)
    # sharded pads each bucket to a device multiple; the params pytree form
    # pads exactly (118272 already divides by 8 -> same as pmean here)
    params = init_mlp(jax.random.key(0))
    assert collectives.bytes_on_wire(params, 8, "sharded") == \
        int(2 * ring * 4 * collectives.padded_size(n, 8))
    # 1 device communicates nothing, whatever the strategy
    for comm in collectives.STRATEGIES:
        assert collectives.bytes_on_wire(n, 1, comm) == 0


def test_padded_size():
    assert collectives.padded_size(16, 8) == 16
    assert collectives.padded_size(17, 8) == 24
    assert collectives.padded_size(1, 8) == 8


def test_sharded_update_bucketization_invariant(mesh):
    """Forcing multi-bucket flattening (tiny bucket budget) produces the
    same update as the single-bucket default — the bucket boundaries are
    pure layout."""
    params = init_mlp(jax.random.key(0))
    grads = jax.tree_util.tree_map(
        lambda a: jnp.full_like(a, 0.25), params)

    def run(bucket_elems):
        f = shard_map(
            lambda p, g: collectives.sharded_update(
                p, g, 0.1, "dp", N_DEV, bucket_elems=bucket_elems),
            mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)
        return jax.tree_util.tree_map(np.asarray, jax.jit(f)(params, grads))

    small = run(1000)   # forces several buckets incl. a padded ragged one
    big = run(collectives.DEFAULT_BUCKET_ELEMS)
    for u, v in zip(_leaves(small), _leaves(big)):
        np.testing.assert_allclose(u, v, rtol=1e-7)
    # and the math is the plain SGD step: p - lr*g exactly (grads equal on
    # every device, so the scattered mean is the input gradient)
    for u, p0 in zip(_leaves(small), _leaves(params)):
        np.testing.assert_allclose(u, np.asarray(p0) - 0.1 * 0.25, rtol=1e-6)


def test_stochastic_round_bf16_neighbors_and_bias():
    """Stochastic rounding lands on one of the two enclosing bf16 values
    and its mean over keys tracks the f32 input more closely than the
    deterministic round-to-nearest cast."""
    x = jnp.linspace(0.001, 1.0, 1024, dtype=jnp.float32)
    keys = jax.random.split(jax.random.key(7), 128)
    rounded = jax.vmap(
        lambda k: collectives.stochastic_round_bf16(k, x))(keys)
    r32 = np.asarray(rounded.astype(jnp.float32))
    xn = np.asarray(x)
    # neighbors: every draw is the truncation or its bf16 successor
    lo = np.asarray(
        jax.lax.bitcast_convert_type(
            jax.lax.bitcast_convert_type(x, jnp.uint32)
            & jnp.uint32(0xFFFF0000), jnp.float32))
    hi = np.asarray(
        jax.lax.bitcast_convert_type(
            (jax.lax.bitcast_convert_type(x, jnp.uint32)
             & jnp.uint32(0xFFFF0000)) + jnp.uint32(0x10000), jnp.float32))
    assert np.all((r32 == lo[None]) | (r32 == hi[None]))
    stoch_bias = np.abs(r32.mean(axis=0) - xn).max()
    det_bias = np.abs(
        np.asarray(x.astype(jnp.bfloat16).astype(jnp.float32)) - xn).max()
    assert stoch_bias < det_bias


def test_comm_probe_runs_every_strategy(mesh):
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    for comm in collectives.STRATEGIES:
        probe = collectives.make_comm_probe(mesh, comm)
        secs = collectives.measure_collective_seconds(probe, params, reps=2)
        assert len(secs) == 2 and all(s > 0 for s in secs)


def test_dp_run_fn_comm_matches_step_loop(mesh):
    """The epoch-scanned DP program with comm='sharded' stays allclose to
    its comm='pmean' twin — the scan layer threads the strategy through
    _dp_step_body identically to the streaming step."""
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    from jax.sharding import NamedSharding

    n_rows = N_DEV * 64
    rng = np.random.default_rng(0)
    x_all = rng.normal(size=(n_rows, 784)).astype(np.float32)
    y_all = rng.integers(0, 10, size=n_rows).astype(np.int32)
    idxs = np.arange(n_rows, dtype=np.int32).reshape(1, 4, N_DEV * 16)

    def run(comm):
        fn = make_dp_run_fn(mesh, lr=0.05, comm=comm)
        rep = replicated(mesh)
        p = jax.device_put(init_mlp(jax.random.key(0)), rep)
        k = jax.device_put(jax.random.key(1), rep)
        out = fn(p, k,
                 jax.device_put(x_all, rep), jax.device_put(y_all, rep),
                 jax.device_put(idxs, NamedSharding(mesh, P(None, None,
                                                            "dp"))))
        return (jax.tree_util.tree_map(np.asarray, out[0]),
                np.asarray(out[2]))

    p_ref, l_ref = run("pmean")
    p_sh, l_sh = run("sharded")
    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-6, atol=1e-7)
    for u, v in zip(_leaves(p_ref), _leaves(p_sh)):
        np.testing.assert_allclose(v, u, rtol=1e-6, atol=1e-7)


def test_pallas_epoch_rejects_comm(mesh):
    from pytorch_ddp_mnist_tpu.train.scan import make_dp_run_fn
    with pytest.raises(ValueError, match="IN-kernel"):
        make_dp_run_fn(mesh, lr=0.01, kernel="pallas_epoch", comm="sharded")


def test_int8_drift_bounded(mesh):
    """The acceptance pin: 3 int8 error-feedback steps stay within a
    bounded envelope of the pmean baseline. The per-step quantization
    error is <= scale/2 per element (scale = blockmax/127), the param
    delta lr * that; with error feedback the bias cancels across steps.
    Observed worst-abs ~1e-5 at lr 0.05 (recorded in docs/PERF.md) — the
    1e-3 pin still fails instantly on a wrong-mean bug (O(grad) ~ 1e-2)."""
    ref, got = _train(mesh, "pmean"), _train(mesh, "int8")
    worst = max(float(np.max(np.abs(u - v)))
                for u, v in zip(_leaves(ref), _leaves(got)))
    assert 0 < worst < 1e-3, worst


def test_int8_step_is_deterministic(mesh):
    """Two independent int8 builds produce bit-identical params — the
    quantization is deterministic (no stochastic rounding), so the drift
    vs pmean is a fixed function of the trajectory, not noise."""
    a, b = _train(mesh, "int8"), _train(mesh, "int8")
    for u, v in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(u, v)


def test_pmean_overlap_matches_baseline(mesh):
    """Bucket-pipelining the pmean collective is pure scheduling: the
    per-element f32 allreduce-mean math is unchanged, so overlap=True
    stays within f32 reassociation tolerance of the untouched baseline
    (observed bitwise-equal on CPU; pinned allclose so a TPU reduction
    reorder cannot flake it)."""
    def train_ov(overlap):
        step = make_dp_train_step(mesh, lr=0.05, comm="pmean",
                                  overlap=overlap)
        params = jax.device_put(init_mlp(jax.random.key(0)),
                                replicated(mesh))
        key = jax.device_put(jax.random.key(1), replicated(mesh))
        x, y = _batch(N_DEV * 16, seed=3)
        for _ in range(3):
            params, key, loss = step(
                params, key,
                jax.device_put(x, batch_sharding(mesh)),
                jax.device_put(y, batch_sharding(mesh)))
        assert np.isfinite(float(loss))
        return jax.tree_util.tree_map(np.asarray, params)

    base, ov = train_ov(False), train_ov(True)
    for u, v in zip(_leaves(base), _leaves(ov)):
        np.testing.assert_allclose(v, u, rtol=1e-6, atol=1e-7)


def test_multi_bucket_parity_every_strategy(mesh):
    """The DEFAULT_BUCKET_ELEMS comment's promise, exercised: every
    strategy run with a bucket budget forcing >= 3 buckets pins against
    its own single-bucket path. Bucket boundaries are pure layout for the
    f32/bf16 collectives (per-element reduction unchanged — rtol 1e-6);
    int8's scaling-block boundaries shift with the concat layout, so its
    pin is the quantization-level envelope instead."""
    small = 1000   # leaf sizes 128/100352/128/16384/1280 -> 5 buckets
    leaves = _leaves(init_mlp(jax.random.key(0)))
    n_buckets = len(collectives._leaf_buckets(leaves, small))
    assert n_buckets >= 3, n_buckets

    def train_b(comm, bucket_elems, overlap):
        step = make_dp_train_step(mesh, lr=0.05, comm=comm,
                                  overlap=overlap,
                                  bucket_elems=bucket_elems)
        params = jax.device_put(init_mlp(jax.random.key(0)),
                                replicated(mesh))
        key = jax.device_put(jax.random.key(1), replicated(mesh))
        resid = (step.place_comm_state(None, params)
                 if step.comm_state else None)
        x, y = _batch(N_DEV * 16, seed=3)
        for _ in range(3):
            xs = jax.device_put(x, batch_sharding(mesh))
            ys = jax.device_put(y, batch_sharding(mesh))
            if step.comm_state:
                params, key, loss, resid = step(params, key, xs, ys, resid)
            else:
                params, key, loss = step(params, key, xs, ys)
        assert np.isfinite(float(loss))
        return jax.tree_util.tree_map(np.asarray, params)

    for comm, overlap, tol in (("pmean", True, None),
                               ("sharded", False, None),
                               ("bf16", True, None),
                               ("int8", False, 1e-3)):
        multi = train_b(comm, small, overlap)
        single = train_b(comm, collectives.DEFAULT_BUCKET_ELEMS, overlap)
        for u, v in zip(_leaves(multi), _leaves(single)):
            if tol is None:
                np.testing.assert_allclose(
                    u, v, rtol=1e-6, atol=1e-7,
                    err_msg=f"{comm} overlap={overlap}")
            else:
                assert float(np.max(np.abs(u - v))) < tol, \
                    (comm, float(np.max(np.abs(u - v))))


def test_quantize_block_int8_properties():
    """Quantization invariants: error <= scale/2 per element, all-zero
    blocks stay exactly zero, block maxima are exactly representable."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=4 * 256).astype(np.float32)
    x[256:512] = 0.0                       # one all-zero block
    q, s = collectives.quantize_block_int8(jnp.asarray(x), 256)
    deq = np.asarray(collectives.dequantize_block_int8(q, s, 256))
    s_np = np.asarray(s)
    assert np.all(np.asarray(q)[256:512] == 0) and s_np[1] == 0
    np.testing.assert_array_equal(deq[256:512], 0.0)
    err = np.abs(deq - x).reshape(-1, 256)
    assert np.all(err <= s_np[:, None] / 2 + 1e-9)
    # the block max itself quantizes to exactly +-127 * scale = itself
    for b in (0, 2, 3):
        i = np.argmax(np.abs(x[b * 256:(b + 1) * 256])) + b * 256
        np.testing.assert_allclose(deq[i], x[i], rtol=1e-6)


def test_int8_allreduce_mean_within_quant_envelope(mesh):
    """The full two-phase quantized allreduce lands within the analytic
    quantization envelope of the exact mean: per phase the per-element
    error is <= scale/2, scales are O(blockmax/127)."""
    rng = np.random.default_rng(5)
    local = rng.normal(size=(N_DEV, 2048)).astype(np.float32)

    def body(g):
        mean, _ = collectives.int8_allreduce_mean(
            g.reshape(-1), None, "dp", N_DEV, 256)
        return mean

    f = shard_map(body, mesh=mesh, in_specs=(P("dp"),), out_specs=P(),
                  check_vma=False)
    got = np.asarray(jax.jit(f)(local))
    want = local.mean(axis=0)
    scale_bound = np.abs(local).max() / 127.0
    assert np.max(np.abs(got - want)) <= scale_bound, \
        (np.max(np.abs(got - want)), scale_bound)


def test_validate_int8_options_rejection_matrix():
    """The knob-hygiene satellite: every int8 knob is rejected BY NAME on
    strategies that would silently ignore it (mirror of
    validate_bf16_rounding), and malformed values are rejected on int8
    itself."""
    # defaults pass everywhere — both the explicit value and the None
    # "unset" sentinel (the CLI default, so retuning QUANT_BLOCK can
    # never make default invocations start failing)
    for comm in collectives.STRATEGIES:
        collectives.validate_int8_options(collectives.QUANT_BLOCK, True,
                                          comm)
        collectives.validate_int8_options(None, True, comm)
    # non-default quant_block off int8: by name
    with pytest.raises(ValueError, match="never quantizes"):
        collectives.validate_int8_options(128, True, "pmean")
    with pytest.raises(ValueError, match="never quantizes"):
        collectives.validate_int8_options(512, True, "bf16")
    # error_feedback off int8: by name
    with pytest.raises(ValueError, match="no quantization error"):
        collectives.validate_int8_options(collectives.QUANT_BLOCK, False,
                                          "sharded")
    # malformed values rejected on any strategy, int8 included
    for bad in (0, 4, -256, "256", 2.5):
        with pytest.raises(ValueError, match="quant_block"):
            collectives.validate_int8_options(bad, True, "int8")
    # int8 itself accepts non-default (valid) values
    collectives.validate_int8_options(64, False, "int8")


def test_int8_knobs_rejected_at_step_builder(mesh):
    with pytest.raises(ValueError, match="never quantizes"):
        make_dp_train_step(mesh, lr=0.01, comm="sharded", quant_block=128)
    with pytest.raises(ValueError, match="no quantization error"):
        make_dp_train_step(mesh, lr=0.01, comm="pmean",
                           error_feedback=False)


def test_bytes_on_wire_int8_pinned():
    """Exact ints for the int8 wire format (the docs/PERF.md numbers):
    118,272 params pad to 118,784 (a multiple of 8 devices * 256 block),
    payload = 1 byte/elem + one f32 scale per 256 = 120,640 bytes, both
    quantized phases move (N-1)/N of it -> 211,120 bytes/device/step on 8
    devices — 25.5% of pmean's 827,904 f32 bytes."""
    params = init_mlp(jax.random.key(0))
    n = param_count(params)
    assert collectives.comm_state_elems(params, 8) == 118784
    assert collectives.bytes_on_wire(params, 8, "int8") == 211120
    assert collectives.bytes_on_wire(n, 8, "int8") == 211120
    pm = collectives.bytes_on_wire(params, 8, "pmean")
    assert pm == 827904
    ratio = collectives.bytes_on_wire(params, 8, "int8") / pm
    assert 0.25 < ratio < 0.26, ratio
    # a larger quant_block shrinks the scale overhead monotonically
    assert (collectives.bytes_on_wire(n, 8, "int8", quant_block=1024)
            < collectives.bytes_on_wire(n, 8, "int8", quant_block=64))


def test_place_comm_state_shape_rejection(mesh):
    """A residual saved under a different mesh size or quantization
    geometry is rejected by name, never silently reinterpreted."""
    params = init_mlp(jax.random.key(0))
    good = collectives.comm_state_zeros(params, N_DEV)
    placed = collectives.place_comm_state(mesh, params)
    assert placed.shape == good.shape
    host = np.asarray(placed)
    np.testing.assert_array_equal(host, 0.0)
    with pytest.raises(ValueError, match="different mesh size"):
        collectives.place_comm_state(
            mesh, params, host=collectives.comm_state_zeros(params, 4))
    with pytest.raises(ValueError, match="different mesh size"):
        collectives.place_comm_state(
            mesh, params,
            host=np.zeros((N_DEV, good.shape[1] + 2048), np.float32))
    with pytest.raises(ValueError, match="needs either"):
        collectives.place_comm_state(mesh, None, host=None)


def test_carries_state_and_apply_gradients_rejects_int8():
    assert collectives.carries_state("int8")
    assert not collectives.carries_state("int8", error_feedback=False)
    for comm in ("pmean", "sharded", "bf16"):
        assert not collectives.carries_state(comm)

    with pytest.raises(ValueError, match="int8_apply_gradients"):
        collectives.apply_gradients({}, {}, 0.01, "dp", "int8", 8)


def test_int8_error_feedback_residual_is_live(mesh):
    """The residual actually changes across steps (the quantization error
    is being carried), and error_feedback=False runs stateless."""
    step = make_dp_train_step(mesh, lr=0.05, comm="int8")
    assert step.comm_state
    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    key = jax.device_put(jax.random.key(1), replicated(mesh))
    resid = step.place_comm_state(None, params)
    x, y = _batch(N_DEV * 16, seed=3)
    xs = jax.device_put(x, batch_sharding(mesh))
    ys = jax.device_put(y, batch_sharding(mesh))
    params, key, _, resid = step(params, key, xs, ys, resid)
    r1 = np.asarray(resid)
    assert np.abs(r1).max() > 0      # quantization error was captured
    off = make_dp_train_step(mesh, lr=0.05, comm="int8",
                             error_feedback=False)
    assert not off.comm_state


def test_ddp_comm_recorder_publishes_metrics(mesh):
    """The train-loop recorder lands ddp.bytes_on_wire in the process
    registry even with telemetry disabled (counter = cheap host math), and
    the probe histogram only when a tracer is live."""
    from pytorch_ddp_mnist_tpu.telemetry import get_registry
    from pytorch_ddp_mnist_tpu.train.loop import make_ddp_comm_recorder

    params = jax.device_put(init_mlp(jax.random.key(0)), replicated(mesh))
    rec = make_ddp_comm_recorder(mesh, "sharded", N_DEV, params)
    reg = get_registry()
    before = reg.counter("ddp.bytes_on_wire").value
    h_before = reg.histogram("ddp.collective_s").n
    rec(10, params)
    per_step = collectives.bytes_on_wire(params, N_DEV, "sharded")
    assert reg.counter("ddp.bytes_on_wire").value == before + 10 * per_step
    # telemetry disabled (NullTracer): no probe reps were recorded
    assert reg.histogram("ddp.collective_s").n == h_before
