"""Data layer: IDX round trip (magic 2049/2051 per the converter notebook),
notebook-cell execution,
normalization parity, synthetic dataset, batch loader shapes."""

import gzip
import os

import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.data import (
    read_idx, write_idx, load_mnist, synthetic_mnist, normalize_images,
    BatchLoader)
from pytorch_ddp_mnist_tpu.data.mnist import MNIST_MEAN, MNIST_STD, get_mnist
from pytorch_ddp_mnist_tpu.parallel import ShardedSampler


def test_idx_image_round_trip(tmp_path):
    arr = np.random.default_rng(0).integers(0, 256, (5, 28, 28), dtype=np.uint8)
    p = str(tmp_path / "imgs-idx3-ubyte")
    write_idx(p, arr)
    out = read_idx(p)
    np.testing.assert_array_equal(arr, out)
    with open(p, "rb") as f:
        assert int.from_bytes(f.read(4), "big") == 2051  # notebook magic check


def test_idx_label_round_trip(tmp_path):
    arr = np.arange(10, dtype=np.uint8)
    p = str(tmp_path / "lbls-idx1-ubyte")
    write_idx(p, arr)
    np.testing.assert_array_equal(arr, read_idx(p))
    with open(p, "rb") as f:
        assert int.from_bytes(f.read(4), "big") == 2049


def test_idx_bad_magic(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\x00\x00\x00\x07rest")
    with pytest.raises(ValueError, match="magic"):
        read_idx(p)


def test_load_mnist_from_idx_and_gz(tmp_path):
    imgs = np.random.default_rng(1).integers(0, 256, (7, 28, 28), dtype=np.uint8)
    lbls = np.arange(7, dtype=np.uint8) % 10
    write_idx(str(tmp_path / "train-images-idx3-ubyte"), imgs)
    # labels as .gz to exercise the gz path (torchvision caches both forms)
    raw_path = tmp_path / "lbl_raw"
    write_idx(str(raw_path), lbls)
    with open(raw_path, "rb") as f, \
            gzip.open(tmp_path / "train-labels-idx1-ubyte.gz", "wb") as g:
        g.write(f.read())
    os.remove(raw_path)
    split = load_mnist(str(tmp_path), train=True)
    np.testing.assert_array_equal(split.images, imgs)
    np.testing.assert_array_equal(split.labels, lbls)
    assert load_mnist(str(tmp_path), train=False) is None
    # get_mnist falls back to synthetic for the missing split
    test_split = get_mnist(str(tmp_path), train=False, synthetic_n=50)
    assert len(test_split) == 50


def test_normalize_matches_reference_transform():
    imgs = np.asarray([[[0, 255]]], dtype=np.uint8)  # (1, 1, 2)
    x = normalize_images(imgs)
    assert x.shape == (1, 2)
    np.testing.assert_allclose(
        x[0], [(0 - MNIST_MEAN) / MNIST_STD, (1.0 - MNIST_MEAN) / MNIST_STD],
        rtol=1e-6)


def test_synthetic_deterministic_and_learnable():
    a = synthetic_mnist(100, seed=0)
    b = synthetic_mnist(100, seed=0)
    np.testing.assert_array_equal(a.images, b.images)
    np.testing.assert_array_equal(a.labels, b.labels)
    assert a.images.dtype == np.uint8 and a.images.shape == (100, 28, 28)
    # class templates differ: mean image per class should be distinguishable
    m0 = a.images[a.labels == a.labels[0]].mean(axis=0)
    other = a.labels[a.labels != a.labels[0]][0]
    m1 = a.images[a.labels == other].mean(axis=0)
    assert np.abs(m0 - m1).mean() > 5


def test_batch_loader_static_shapes_and_coverage():
    split = synthetic_mnist(130, seed=3)
    x = normalize_images(split.images)
    sampler = ShardedSampler(130, num_replicas=2, rank=0, shuffle=True)
    loader = BatchLoader(x, split.labels, sampler, batch_size=32)
    batches = list(loader)
    assert len(batches) == len(loader) == 3  # ceil(65/32)
    for bx, by in batches:
        assert bx.shape == (32, 784) and by.shape == (32,)
        assert by.dtype == np.int32  # uint8 -> int32 cast (SURVEY §7 item 9)


def test_batch_loader_iter_from_skips_without_gathering():
    """iter_from(n) (the mid-epoch resume path) yields exactly the tail of
    a full iteration — and never indexes the skipped batches' rows."""
    split = synthetic_mnist(130, seed=3)
    x = normalize_images(split.images)
    sampler = ShardedSampler(130, num_replicas=2, rank=0, shuffle=True)
    loader = BatchLoader(x, split.labels, sampler, batch_size=32)
    full = list(loader)
    tail = list(loader.iter_from(2))
    assert len(tail) == len(full) - 2
    for (fx, fy), (tx, ty) in zip(full[2:], tail):
        np.testing.assert_array_equal(fx, tx)
        np.testing.assert_array_equal(fy, ty)

    class Booby(np.ndarray):
        def __getitem__(self, idx):
            raise AssertionError("skipped batches must never be gathered")

    # skipping EVERYTHING must touch no rows at all
    loader.images = np.asarray(x).view(Booby)
    assert list(loader.iter_from(len(full))) == []


def test_device_prefetch_order_and_edges():
    """device_prefetch must yield every batch, in order, with one batch of
    lookahead — including the 1-batch and 0-batch edge cases."""
    import jax
    from pytorch_ddp_mnist_tpu.data import device_prefetch

    batches = [(np.full((4, 784), i, np.float32), np.full((4,), i, np.int32))
               for i in range(5)]
    out = list(device_prefetch(batches))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert isinstance(x, jax.Array) and isinstance(y, jax.Array)
        assert float(x[0, 0]) == i and int(y[0]) == i

    assert len(list(device_prefetch(batches[:1]))) == 1
    assert list(device_prefetch([])) == []


def test_converter_notebook_cells_execute(tmp_path, monkeypatch, capsys):
    """notebooks/mnist_to_netcdf.ipynb (the reference notebook's analog) must
    actually run: exec its code cells in order against small fixture IDX
    files and check the .nc outputs it reports."""
    import json
    import os

    from pytorch_ddp_mnist_tpu.data.idx import write_idx

    rng = np.random.default_rng(0)
    idx_dir, nc_dir = tmp_path / "idx", tmp_path / "nc"
    idx_dir.mkdir(), nc_dir.mkdir()
    for prefix, n in (("train", 64), ("t10k", 16)):
        write_idx(str(idx_dir / f"{prefix}-images-idx3-ubyte"),
                  rng.integers(0, 256, (n, 28, 28), dtype=np.uint8))
        write_idx(str(idx_dir / f"{prefix}-labels-idx1-ubyte"),
                  rng.integers(0, 10, (n,), dtype=np.uint8))
    monkeypatch.setenv("MNIST_IDX_DIR", str(idx_dir))
    monkeypatch.setenv("MNIST_NC_DIR", str(nc_dir))

    nb_path = os.path.join(os.path.dirname(__file__), "..", "notebooks",
                           "mnist_to_netcdf.ipynb")
    with open(nb_path) as f:
        nb = json.load(f)
    cells = [c for c in nb["cells"] if c["cell_type"] == "code"]
    assert cells, "notebook has no code cells"
    ns = {}
    for cell in cells:
        exec("".join(cell["source"]), ns)  # noqa: S102 — our own notebook

    out = capsys.readouterr().out
    assert "round-trip OK" in out
    assert (nc_dir / "mnist_train_images.nc").exists()
    assert (nc_dir / "mnist_test_images.nc").exists()
