"""Performance ledger (telemetry/ledger.py + cli/ledger.py): the
ingestion pin matrix over every artifact actually committed in-repo, the
direction-aware trend gate, the shared workload normalizer, and the
ledger stamps.

The pin matrix is the schema-drift tripwire ISSUE 18 asks for: any future
change to bench.py's artifact shapes fails HERE by name before an
artifact lands — exact per-generation row counts and one golden row per
generation, against the real committed files (zero fixtures)."""

from __future__ import annotations

import json
import math
import os

import pytest

from pytorch_ddp_mnist_tpu.telemetry import analysis, export
from pytorch_ddp_mnist_tpu.telemetry import ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Every committed artifact generation, with its exact canonical-row count.
# A new artifact lands => add its line; a count drift => bench.py (or a
# loader) changed schema without teaching the ledger.
COMMITTED_ROW_COUNTS = {
    "BENCH_r01.json": 1,       # driver-wrapped bench line
    "COST_r01.json": 11,       # compile/HBM summary + 8 program rows
    "INPUT_r01.json": 10,      # headline + legacy/pipeline + compiles
    "MULTICHIP_r01.json": 1,   # legacy ok bit
    "MULTICHIP_r02.json": 1,
    "MULTICHIP_r03.json": 1,
    "MULTICHIP_r04.json": 1,
    "MULTICHIP_r05.json": 1,
    "MULTICHIP_r06.json": 22,  # ok + 3 strategy rows x 7 metrics
    "MULTICHIP_r07.json": 57,  # ok + 8 rows x 7 metrics
    "MULTICHIP_r08.json": 97,  # ok + 8 rows x 12 metrics
    "SERVE_r01.json": 9,       # 2 paths x 4 knee metrics + qps_gain
    "bench_matrix_r03.json": 8,
    "bench_matrix_r05.json": 9,    # 12 variants, 3 null (probe hang)
}
# Driver-wrapped rounds whose backend never came up: SKIPPED with their
# recorded reason, never ingested as zeros and never silently dropped.
COMMITTED_SKIPS = {"BENCH_r02.json": 1, "BENCH_r03.json": 1,
                   "BENCH_r04.json": 1, "BENCH_r05.json": 1,
                   "bench_matrix_r05.json": 3}


@pytest.fixture(scope="module")
def committed():
    paths = ledger.discover(REPO)
    return ledger.ingest(paths)


def _rows(committed, **kw):
    return [r for r in committed["rows"]
            if all(r[k] == v for k, v in kw.items())]


# ---------------------------------------------------------------- ingest

def test_pin_matrix_counts(committed):
    assert committed["artifacts"] == 18
    by_source: dict = {}
    for r in committed["rows"]:
        by_source[r["source"]] = by_source.get(r["source"], 0) + 1
    assert by_source == COMMITTED_ROW_COUNTS
    skips: dict = {}
    for s in committed["skipped"]:
        skips[s["source"]] = skips.get(s["source"], 0) + 1
    assert skips == COMMITTED_SKIPS
    assert len(committed["rows"]) == 229


def test_pin_matrix_series_and_families(committed):
    rep = ledger.report(committed["rows"])
    assert rep["n_series"] == 223
    assert rep["families"] == ["bench", "cost", "ddp", "input", "matrix",
                               "multichip", "serve"]


def test_golden_row_bench_wrapped(committed):
    (row,) = _rows(committed, source="BENCH_r01.json")
    assert row == {
        "series": "bench.train_images_per_sec_per_chip/mlp x1/?",
        "metric": "bench.train_images_per_sec_per_chip", "variant": None,
        "model": "mlp", "param_scale": 1, "n_devices": None,
        "per_chip_batch": None, "backend": None, "value": 7545951.8,
        "direction": "higher_better", "run_ord": 1,
        "source": "BENCH_r01.json", "unit": "images/sec/chip"}


def test_golden_row_multichip_legacy(committed):
    (row,) = _rows(committed, source="MULTICHIP_r01.json")
    assert row["series"] == "multichip.ok/mlp x1/8dev/?"
    assert row["value"] == 1.0
    assert row["direction"] == "higher_better"
    assert row["run_ord"] == 1


def test_golden_row_multichip_strategies(committed):
    row = _rows(committed, source="MULTICHIP_r08.json",
                metric="ddp.images_per_sec", variant="bf16+overlap")[0]
    assert row["series"] == \
        "ddp.images_per_sec/bf16+overlap/mlp x8/8dev/b4/cpu"
    assert row["value"] == 383.0
    assert (row["model"], row["param_scale"], row["n_devices"],
            row["per_chip_batch"], row["backend"]) == ("mlp", 8, 8, 4,
                                                       "cpu")


def test_golden_row_cost(committed):
    (row,) = _rows(committed, source="COST_r01.json",
                   metric="cost.peak_hbm_bytes")
    assert row["series"] == "cost.peak_hbm_bytes/mlp x16/8dev/b4/cpu"
    assert row["value"] == 130073956.0
    assert row["direction"] == "lower_better"
    effs = _rows(committed, source="COST_r01.json",
                 metric="cost.analytic_efficiency")
    assert len(effs) == 8 and all(e["variant"].startswith("ddp.step.")
                                  for e in effs)


def test_golden_row_serve(committed):
    (row,) = _rows(committed, metric="serve.max_sustained_qps",
                   variant="legacy")
    assert row["series"] == "serve.max_sustained_qps/legacy/mlp x1/cpu"
    assert row["value"] == 19772.84
    (p99,) = _rows(committed, metric="serve.p99_ms", variant="fast")
    assert p99["value"] == 2.423 and p99["direction"] == "lower_better"


def test_golden_row_input(committed):
    (row,) = _rows(committed, metric="input.data_wait_share_p95",
                   variant="pipeline")
    assert row["series"] == "input.data_wait_share_p95/pipeline/mlp x1/?"
    assert row["value"] == 0.3061
    assert row["direction"] == "lower_better"


def test_golden_row_bench_matrix(committed):
    row = _rows(committed, source="bench_matrix_r03.json",
                variant="bf16 / XLA / rbg")[0]
    assert row["series"] == \
        "matrix.images_per_sec_per_chip/bf16 / XLA / rbg/mlp x1/tpu"
    assert row["value"] == 14709051.8
    assert row["run_ord"] == 3
    # STRICT backend matching: r05's backend-null rerun of the same label
    # must NOT join r03's tpu series
    r05 = _rows(committed, source="bench_matrix_r05.json")
    assert all(r["backend"] is None for r in r05)


def test_multichip_ok_forms_multi_run_series(committed):
    hist = ledger.histories(committed["rows"])
    legacy = hist["multichip.ok/mlp x1/8dev/?"]
    assert [r["run_ord"] for r in legacy] == [1, 2, 3, 4, 5]
    modern = hist["multichip.ok/mlp x1/8dev/cpu"]
    assert [r["run_ord"] for r in modern] == [6, 7, 8]
    assert all(r["value"] == 1.0 for r in legacy + modern)


# ------------------------------------------------- generation detection

def test_detect_generation_refuses_unknown(tmp_path):
    p = tmp_path / "MULTICHIP_r99.json"
    p.write_text(json.dumps({"something": 1, "else": 2}))
    with pytest.raises(ledger.LedgerError) as ei:
        ledger.load_artifact(str(p))
    assert "MULTICHIP_r99.json" in str(ei.value)
    assert "generation" in str(ei.value)


def test_unknown_bench_metric_fails_by_name(tmp_path):
    p = tmp_path / "BENCH_r42.json"
    p.write_text(json.dumps({"metric": "mnist_new_hotness", "value": 1.0}))
    with pytest.raises(ledger.LedgerError) as ei:
        ledger.load_artifact(str(p))
    assert "mnist_new_hotness" in str(ei.value)
    assert "direction" in str(ei.value)


def test_schema_version_grandfather_and_refusal(tmp_path):
    assert ledger.check_schema_version({}, "x") == 1
    assert ledger.check_schema_version({"schema_version": 2}, "x") == 2
    with pytest.raises(ledger.LedgerError) as ei:
        ledger.check_schema_version({"schema_version": 3}, "FUT.json")
    assert "FUT.json" in str(ei.value) and "3" in str(ei.value)


def test_run_ordinal_precedence(tmp_path):
    assert ledger.run_ordinal({"run_ord": 12, "n": 3}, "A_r01.json") == 12
    assert ledger.run_ordinal({"n": 3}, "A_r01.json") == 3
    assert ledger.run_ordinal({}, "A_r07.json") == 7
    assert ledger.run_ordinal({}, "whatever.json") == 0


def test_discover_ignores_non_artifacts(tmp_path):
    (tmp_path / "BASELINE.json").write_text("{}")
    (tmp_path / "BENCH_r01.json").write_text("{}")
    found = ledger.discover(str(tmp_path))
    assert [os.path.basename(p) for p in found] == ["BENCH_r01.json"]


# ------------------------------------------------------- trend and gate

def _mk(series_values, direction="higher_better"):
    return [{"series": "s", "metric": "m.x", "variant": None,
             "model": "mlp", "param_scale": 1, "n_devices": None,
             "per_chip_batch": None, "backend": None, "value": v,
             "direction": direction, "run_ord": i + 1,
             "source": f"r{i + 1:02d}", "unit": None}
            for i, v in enumerate(series_values)]


def test_gate_pairwise_degenerate_case():
    # ONE prior point: MAD 0, the band collapses — exactly the old
    # pairwise ratio gate
    stats = ledger.trend(_mk([100.0, 40.0]))
    assert stats["regressed"] and stats["ratio"] == pytest.approx(2.5)
    assert not ledger.trend(_mk([100.0, 90.0]))["regressed"]


def test_gate_mad_band_tolerates_noisy_series():
    # history median 14, MAD 2 -> band 6: a dip to 9 clears the ratio
    # threshold but sits INSIDE the band (jitter), 7 falls outside (real)
    base = [10.0, 12.0, 14.0, 16.0, 18.0]
    inside = ledger.trend(_mk(base + [9.0]))
    assert inside["ratio"] > 1.5 and not inside["regressed"]
    outside = ledger.trend(_mk(base + [7.0]))
    assert outside["regressed"]


def test_gate_lower_better_direction():
    stats = ledger.trend(_mk([2.0, 2.0, 2.0, 4.1], "lower_better"))
    assert stats["regressed"] and stats["ratio"] == pytest.approx(2.05)
    # improvement in a lower_better series never regresses
    assert not ledger.trend(_mk([2.0, 2.0, 1.0],
                                "lower_better"))["regressed"]


def test_gate_collapse_to_zero_is_infinitely_worse():
    stats = ledger.trend(_mk([1.0, 1.0, 1.0, 0.0]))
    assert stats["regressed"] and math.isinf(stats["ratio"])


def test_streak_counts_consecutive_worse():
    assert ledger.trend(_mk([5.0, 4.0, 3.0, 2.9]))["streak"] == 3
    assert ledger.trend(_mk([5.0, 4.0, 6.0]))["streak"] == 0
    assert ledger.trend(_mk([1.0, 2.0, 3.0],
                            "lower_better"))["streak"] == 2


def test_gate_window_bounds_history():
    # ancient good runs outside the window must not mask a slow rot
    values = [100.0] * 3 + [10.0] * 5 + [4.0]
    stats = ledger.trend(_mk(values), window=5)
    assert stats["center"] == 10.0 and stats["regressed"]


def test_gate_names_series_and_run(committed):
    rows = committed["rows"] + _mk([1.0])  # disjoint single-point series
    rep = ledger.gate(rows)
    assert rep["ok"] and rep["failures"] == []
    bad = dict(rows[-1], value=0.25, run_ord=99, source="MULTICHIP_r99")
    good = dict(rows[-1], value=1.0, run_ord=98, source="MULTICHIP_r98")
    rep = ledger.gate(rows + [good, bad])
    assert not rep["ok"]
    assert any("MULTICHIP_r99" in f and f.startswith("s:")
               and "r99" in f for f in rep["failures"])


def test_report_markdown_renders_every_series(committed):
    rep = ledger.report(committed["rows"])
    md = ledger.render_markdown(rep)
    body = [ln for ln in md.splitlines()
            if ln.startswith("| ") and not ln.startswith("| series")]
    assert len(body) == rep["n_series"]
    assert "223 series" in md


# --------------------------------------- shared normalizer + validators

def test_normalize_workload_legacy_defaults():
    wl = analysis.normalize_workload({})
    assert wl == {"model": "mlp", "param_scale": 1, "n_devices": None,
                  "per_chip_batch": None}
    wl = analysis.normalize_workload({"n_devices": 4},
                                     {"model": "tf", "param_scale": 2})
    assert wl == {"model": "tf", "param_scale": 2, "n_devices": 4,
                  "per_chip_batch": None}


def test_strategy_row_label_matches_efficiency_report():
    # the ONE shared rule: efficiency_report's gate labels must be built
    # from the same normalizer the ledger keys series with
    art = {"n_devices": 8}
    row = {"strategy": "pmean", "overlap": True, "model": "mlp",
           "param_scale": 16, "scaling_efficiency_vs_1dev": 0.5}
    assert analysis.strategy_row_label(row, art) == \
        "pmean+overlap@mlp x16@8dev"
    rep = analysis.efficiency_report({"n_devices": 8,
                                      "strategies": [row]})
    assert list(rep["efficiency"]) == ["pmean+overlap@mlp x16@8dev"]
    legacy = {"strategy": "allreduce", "scaling_efficiency_vs_1dev": 0.9}
    assert analysis.strategy_row_label(legacy, art) == "allreduce@8dev"


def test_ledger_row_errors_contract():
    ok = {"kind": "point", "name": "ledger_row", "_line": 1,
          "attrs": {"series": "s", "direction": "higher_better",
                    "value": 1.0}}
    assert analysis.ledger_row_errors([ok]) == []
    bad = [
        {"kind": "point", "name": "ledger_row", "_line": 2,
         "attrs": {"series": "", "direction": "higher_better",
                   "value": 1.0}},
        {"kind": "point", "name": "ledger_row", "_line": 3,
         "attrs": {"series": "s", "direction": "sideways", "value": 1.0}},
        {"kind": "point", "name": "ledger_row", "_line": 4,
         "attrs": {"series": "s", "direction": "lower_better",
                   "value": float("nan")}},
    ]
    errors = analysis.ledger_row_errors([ok] + bad)
    assert [line for line, _ in errors] == [2, 3, 4]
    assert "series" in errors[0][1]
    assert "sideways" in errors[1][1]
    assert "finite" in errors[2][1]
    # other point kinds pass through untouched
    assert analysis.ledger_row_errors(
        [{"kind": "point", "name": "health", "attrs": {}}]) == []


def test_directions_registry_is_total(committed):
    directions = ledger.metric_directions()
    for row in committed["rows"]:
        assert directions[row["metric"]] == row["direction"]


# -------------------------------------------------- stamps + round trip

def test_ledger_stamp_fields_contract(monkeypatch):
    from bench import ledger_stamp_fields
    monkeypatch.setenv("PDMT_RUN_ORD", "17")
    stamp = ledger_stamp_fields()
    assert stamp == {"schema_version": ledger.SCHEMA_VERSION,
                     "run_ord": 17}
    monkeypatch.delenv("PDMT_RUN_ORD")
    stamp = ledger_stamp_fields()
    assert stamp["schema_version"] == ledger.SCHEMA_VERSION
    assert isinstance(stamp["run_ord"], int) and stamp["run_ord"] > 0


def test_multichip_smoke_inline_stamp_pinned():
    # multichip_smoke inlines the stamp (its failed-backend path must not
    # import jax); the inline constant must track ledger.SCHEMA_VERSION
    path = os.path.join(REPO, "scripts", "multichip_smoke.py")
    with open(path) as f:
        src = f.read()
    assert f'artifact["schema_version"] = {ledger.SCHEMA_VERSION}' in src
    assert ledger.SCHEMA_VERSION == 2


def test_stamped_artifact_round_trips(tmp_path, committed):
    # a v2-stamped line ingests with its explicit run_ord winning over
    # the filename convention
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({
        "metric": "mnist_train_images_per_sec_per_chip", "value": 5.0,
        "unit": "images/sec/chip", "schema_version": 2, "run_ord": 42}))
    rows, skipped = ledger.load_artifact(str(p))
    assert not skipped
    assert rows[0]["run_ord"] == 42
    assert rows[0]["series"] == \
        committed["rows"][0]["series"].replace("x1/?", "x1/?")  # same key
    assert rows[0]["metric"] == "bench.train_images_per_sec_per_chip"


def test_export_ledger_counter_tracks(committed):
    hist = ledger.histories(committed["rows"])
    trace = export.chrome_trace([], ledger_series=hist)
    counters = [e for e in trace["traceEvents"]
                if e.get("ph") == "C" and e.get("cat") == "ledger"]
    assert len(counters) == len(committed["rows"])
    assert all(e["pid"] == export.LEDGER_PID for e in counters)
    legacy_ok = [e for e in counters
                 if e["name"] == "multichip.ok/mlp x1/8dev/?"]
    assert [e["ts"] for e in legacy_ok] == [0.0, 1e6, 2e6, 3e6, 4e6]
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "performance ledger" in names
