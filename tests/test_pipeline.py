"""The staged input pipeline (pytorch_ddp_mnist_tpu/pipeline/): reader
plan/load split, background decode workers (order, backpressure, failure
propagation, shutdown), depth-K device prefetch (incl. the deterministic-
teardown fix device_prefetch inherited), the synthetic source, the data.*
telemetry, and THE acceptance pins — pipeline-fed `fit`/`fit_cached`
BITWISE identical to the unpiped paths, with zero new host syncs."""

import threading
import time

import numpy as np
import pytest

import jax

from pytorch_ddp_mnist_tpu.data import (BatchLoader, normalize_images,
                                        synthetic_mnist)
from pytorch_ddp_mnist_tpu.data.loader import device_prefetch
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
from pytorch_ddp_mnist_tpu.pipeline import (ShardReader, SyntheticSource,
                                            WorkerPool, feed, host_iter,
                                            pipeline_capable, prefetch)
from pytorch_ddp_mnist_tpu.statics import sanitize
from pytorch_ddp_mnist_tpu.telemetry import MetricsRegistry
from pytorch_ddp_mnist_tpu.train import TrainState, fit
from pytorch_ddp_mnist_tpu.train.scan import fit_cached
from pytorch_ddp_mnist_tpu.utils import faultpoints


def _batch_loader(n=256, batch=32, seed=42):
    split = synthetic_mnist(n, seed=0)
    sampler = ShardedSampler(n, num_replicas=1, rank=0, seed=seed)
    return BatchLoader(normalize_images(split.images), split.labels,
                       sampler, batch_size=batch)


def _materialize(it):
    return [(np.asarray(x), np.asarray(y)) for x, y in it]


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for (xa, ya), (xb, yb) in zip(a, b):
        assert np.array_equal(xa, xb)
        assert np.array_equal(ya, yb)


# ---------------------------------------------------------------------------
# synthetic source
# ---------------------------------------------------------------------------

def test_synthetic_source_deterministic_and_reshuffled():
    a = SyntheticSource(6, 16, seed=3)
    b = SyntheticSource(6, 16, seed=3)
    a.sampler.set_epoch(1)
    b.sampler.set_epoch(1)
    _assert_batches_equal(_materialize(a), _materialize(b))
    # a different epoch reshuffles (like the real loaders)
    b.sampler.set_epoch(2)
    xa = np.asarray(next(iter(a))[0])
    xb = np.asarray(next(iter(b))[0])
    assert not np.array_equal(xa, xb)


def test_synthetic_source_iter_from_drops_head():
    src = SyntheticSource(6, 16, seed=3)
    src.sampler.set_epoch(0)
    _assert_batches_equal(list(_materialize(src))[2:],
                          _materialize(src.iter_from(2)))


def test_synthetic_source_is_pipeline_capable():
    assert pipeline_capable(SyntheticSource(2, 8))
    assert pipeline_capable(_batch_loader())
    assert not pipeline_capable(iter([(1, 2)]))


# ---------------------------------------------------------------------------
# worker pool
# ---------------------------------------------------------------------------

def test_worker_pool_order_bitwise_vs_sequential():
    loader = _batch_loader()
    loader.sampler.set_epoch(0)
    want = _materialize(loader)
    for workers in (1, 3):
        got = _materialize(WorkerPool(ShardReader(_reshuffled(loader)),
                                      workers, registry=MetricsRegistry()))
        _assert_batches_equal(want, got)


def _reshuffled(loader):
    # same sampler state object — the pool reads the CURRENT epoch like
    # sequential iteration does
    return loader


def test_worker_pool_start_offset_skips_at_index_level():
    loader = _batch_loader()
    loader.sampler.set_epoch(1)
    want = _materialize(loader)[3:]
    got = _materialize(WorkerPool(ShardReader(loader), 2, start=3,
                                  registry=MetricsRegistry()))
    _assert_batches_equal(want, got)


def test_worker_pool_propagates_error_in_order_and_joins():
    class Boom(SyntheticSource):
        def read_batch(self, rows):
            x, y = super().read_batch(rows)
            if int(y[0]) == int(self._boom_row % self.classes) \
                    and np.array_equal(rows, self._boom_rows):
                raise RuntimeError("decode failed at batch 3")
            return x, y

    src = Boom(8, 4, seed=5)
    src.sampler.set_epoch(0)
    order = src.sampler.indices()
    src._boom_rows = order[3 * 4:4 * 4]
    src._boom_row = src._boom_rows[0]
    got = 0
    with pytest.raises(RuntimeError, match="decode failed at batch 3"):
        for _ in WorkerPool(ShardReader(src), 3,
                            registry=MetricsRegistry()):
            got += 1
    assert got == 3          # every batch BEFORE the failure arrived first
    deadline = time.time() + 5
    while time.time() < deadline and any(
            t.is_alive() for t in threading.enumerate()
            if t.name.startswith("pdmt-input-worker")):
        time.sleep(0.05)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("pdmt-input-worker") and t.is_alive()]


def test_worker_pool_early_consumer_exit_joins_workers():
    src = SyntheticSource(16, 8, latency_s=0.005, seed=0)
    src.sampler.set_epoch(0)
    it = iter(WorkerPool(ShardReader(src), 2, registry=MetricsRegistry()))
    next(it)
    it.close()               # mid-epoch abandon: shutdown must be clean
    deadline = time.time() + 5
    while time.time() < deadline and any(
            t.is_alive() for t in threading.enumerate()
            if t.name.startswith("pdmt-input-worker")):
        time.sleep(0.05)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("pdmt-input-worker") and t.is_alive()]


def test_worker_pool_is_one_shot():
    src = SyntheticSource(2, 8, seed=0)
    src.sampler.set_epoch(0)
    pool = WorkerPool(ShardReader(src), 1, registry=MetricsRegistry())
    _materialize(pool)
    with pytest.raises(RuntimeError, match="one-shot"):
        iter(pool)


def test_worker_pool_rejects_bad_knobs():
    reader = ShardReader(SyntheticSource(2, 8))
    with pytest.raises(ValueError, match="num_workers"):
        WorkerPool(reader, 0, registry=MetricsRegistry())
    with pytest.raises(ValueError, match="queue_depth"):
        WorkerPool(reader, 1, queue_depth=0, registry=MetricsRegistry())


def test_host_iter_rejects_uncapable_source_with_workers():
    with pytest.raises(ValueError, match="not pipeline-capable"):
        host_iter(iter([(1, 2)]), workers=2)


def test_loader_stall_fires_inside_worker():
    """The chaos contract: a loader_stall spec stalls PRODUCTION (the
    worker thread), the batch still arrives, and the spec records as
    fired — the watchdog-visible degradation path."""
    inj = faultpoints.install("loader_stall:batch=1:delay_s=0.25")
    try:
        src = SyntheticSource(4, 8, seed=0)
        src.sampler.set_epoch(0)
        t0 = time.perf_counter()
        got = _materialize(WorkerPool(ShardReader(src), 1,
                                      registry=MetricsRegistry()))
        dt = time.perf_counter() - t0
        assert len(got) == 4
        assert inj.specs[0].fired == 1
        assert dt >= 0.25    # the stall really happened, in the worker
    finally:
        faultpoints.install("")


def test_worker_pool_publishes_data_metrics():
    reg = MetricsRegistry()
    src = SyntheticSource(5, 8, seed=0)
    src.sampler.set_epoch(0)
    _materialize(WorkerPool(ShardReader(src), 2, registry=reg))
    snap = reg.snapshot()
    assert snap["histograms"]["data.batch_wait_s"]["n"] == 5
    assert snap["counters"]["data.batches"] == 5
    assert "data.queue_depth" in snap["gauges"]
    assert snap["gauges"]["data.workers"] == 2


def test_sequential_host_iter_publishes_data_metrics():
    reg = MetricsRegistry()
    src = SyntheticSource(5, 8, seed=0)
    src.sampler.set_epoch(0)
    _materialize(host_iter(src, workers=0, registry=reg))
    snap = reg.snapshot()
    assert snap["histograms"]["data.batch_wait_s"]["n"] == 5
    assert snap["counters"]["data.batches"] == 5


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------

def test_prefetch_preserves_order_at_any_depth():
    items = [np.full((4,), i, np.float32) for i in range(7)]
    for depth in (1, 2, 3, 10):   # depth > len shrinks the window
        out = list(prefetch(iter(items), depth=depth,
                            put=lambda b: b + 0.0))
        assert len(out) == 7
        for i, o in enumerate(out):
            assert np.array_equal(np.asarray(o), items[i])


def test_prefetch_rejects_bad_depth_eagerly():
    with pytest.raises(ValueError, match="depth"):
        prefetch([], depth=0)    # no next() needed: validation is eager


def test_prefetch_teardown_drains_and_reraises_original():
    """The device_prefetch fix (ISSUE 12 satellite): a producer exception
    mid-iteration drains every pending transfer (block_until_ready) and
    re-raises the ORIGINAL error — never a secondary one, never
    silently."""
    def gen():
        yield np.ones(4, np.float32)
        yield np.ones(4, np.float32)
        raise ValueError("producer died mid-epoch")

    with sanitize.no_host_sync(max_block_until_ready=None) as s:
        with pytest.raises(ValueError, match="producer died mid-epoch"):
            list(prefetch(gen(), depth=2))
    # the two dispatched transfers were drained during teardown
    assert s.block_until_ready_calls >= 2


def test_prefetch_consumer_close_drains_every_dispatched_transfer():
    """The consumer-abandon half of the teardown contract: closing the
    generator at the yield point (what a raising train step does to the
    feed) must drain EVERY dispatched transfer — including the one
    dispatched for the yield in progress."""
    dispatched = []

    def put(b):
        dispatched.append(b)
        return b

    drained = []

    class _Probe:
        def __init__(self, i):
            self.i = i

    items = [_Probe(i) for i in range(6)]
    import importlib
    # the package re-exports the FUNCTION under the submodule's name, so
    # plain `import ...pipeline.prefetch` resolves to the function
    pf = importlib.import_module("pytorch_ddp_mnist_tpu.pipeline.prefetch")

    orig = pf._drain

    def spying_drain(pending):
        drained.extend(pending)
        pending.clear()

    pf._drain = spying_drain
    try:
        it = prefetch(iter(items), depth=2, put=put)
        got = [next(it), next(it)]
        it.close()
    finally:
        pf._drain = orig
    # every dispatched-but-unyielded transfer was handed to the drain
    assert {p.i for p in dispatched} - {p.i for p in got} \
        == {p.i for p in drained}
    assert drained, "nothing drained — the in-flight window leaked"


def test_device_prefetch_alias_delegates_to_pipeline():
    loader = _batch_loader()
    loader.sampler.set_epoch(0)
    want = _materialize(loader)
    got = _materialize(device_prefetch(loader))
    _assert_batches_equal(want, got)


def test_feed_parity_all_configurations():
    src0 = SyntheticSource(8, 16, seed=7)
    src0.sampler.set_epoch(0)
    want = _materialize(src0)
    for workers, depth, start in ((0, 1, 0), (0, 3, 2), (2, 1, 0),
                                  (3, 2, 3)):
        src = SyntheticSource(8, 16, seed=7)
        src.sampler.set_epoch(0)
        got = _materialize(feed(src, workers=workers, depth=depth,
                                start=start, registry=MetricsRegistry()))
        _assert_batches_equal(want[start:], got)


# ---------------------------------------------------------------------------
# the acceptance pins: pipeline-fed trainers stay BITWISE
# ---------------------------------------------------------------------------

def _fit_params(workers, depth):
    split = synthetic_mnist(256, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(256, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(split.images), split.labels,
                         sampler, batch_size=32)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    out = fit(state, loader, normalize_images(test.images),
              test.labels.astype(np.int32), epochs=2, batch_size=32,
              lr=0.1, log=lambda _m: None,
              input_workers=workers, prefetch_depth=depth)
    return jax.tree_util.tree_map(np.asarray, out.params)


def test_fit_pipeline_bitwise_parity():
    """Legacy-loader parity pin (ISSUE 12 acceptance): same seed + same
    source -> pipeline-fed fit is BITWISE identical to the unpiped path."""
    want = jax.tree_util.tree_leaves(_fit_params(0, 1))
    got = jax.tree_util.tree_leaves(_fit_params(3, 2))
    assert all(np.array_equal(a, b) for a, b in zip(got, want))


def _fit_cached_params(depth, every=0):
    split = synthetic_mnist(256, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(256, num_replicas=1, rank=0, seed=42)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    out = fit_cached(state, split.images, split.labels.astype(np.int32),
                     sampler, normalize_images(test.images),
                     test.labels.astype(np.int32), epochs=2, batch_size=32,
                     lr=0.1, log=lambda _m: None, ckpt_every_steps=every,
                     prefetch_depth=depth)
    return jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, out.params))


def test_fit_cached_prefetch_bitwise_parity():
    """The fit_cached half of the parity pin: depth-K chunk-placement
    prefetch is bitwise, chunked or not."""
    want = _fit_cached_params(1)
    assert all(np.array_equal(a, b)
               for a, b in zip(_fit_cached_params(3), want))
    chunk_want = _fit_cached_params(1, every=3)
    assert all(np.array_equal(a, b)
               for a, b in zip(_fit_cached_params(3, every=3), chunk_want))
    # chunking itself stays invariant under prefetch too
    assert all(np.array_equal(a, b) for a, b in zip(chunk_want, want))


def test_fit_pipeline_zero_new_host_syncs():
    """The ISSUE 12 sync contract: worker threads, yes — consumer-side
    host syncs, ZERO. The PR 10 epoch-granular fetch budget holds with
    the pipeline on."""
    split = synthetic_mnist(128, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(split.images), split.labels,
                         sampler, batch_size=32)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    epochs = 2
    with sanitize.no_host_sync(max_fetches=epochs * 6) as s:
        fit(state, loader, normalize_images(test.images),
            test.labels.astype(np.int32), epochs=epochs, batch_size=32,
            lr=0.1, log=lambda _m: None, input_workers=2,
            prefetch_depth=2)
    assert s.block_until_ready_calls == 0


def test_fit_mid_epoch_resume_through_pipeline_in_process():
    """In-process mid-epoch resume parity with workers live: capture the
    state a step checkpoint would commit mid-epoch, resume a piped fit
    from it, finish bitwise on the unbroken PIPED (== unpiped) run."""
    def build():
        split = synthetic_mnist(256, seed=0)
        test = synthetic_mnist(64, seed=1)
        sampler = ShardedSampler(256, num_replicas=1, rank=0, seed=42)
        loader = BatchLoader(normalize_images(split.images), split.labels,
                             sampler, batch_size=32)
        return (loader, normalize_images(test.images),
                test.labels.astype(np.int32))

    saved = {}

    def hook(ep, off, gs, st):
        if gs == 3:          # a mid-epoch position (8 steps/epoch)
            saved["state"] = TrainState(
                jax.tree_util.tree_map(np.asarray, st.params),
                np.asarray(jax.random.key_data(st.key)))
            saved["pos"] = (ep, off)

    loader, x_test, y_test = build()
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    unbroken = fit(state, loader, x_test, y_test, epochs=2, batch_size=32,
                   lr=0.1, log=lambda _m: None, ckpt_every_steps=3,
                   step_hook=hook, input_workers=2, prefetch_depth=2)
    assert saved["pos"][1] != 0      # genuinely mid-epoch

    loader2, x_test2, y_test2 = build()
    resumed_state = TrainState(
        jax.tree_util.tree_map(jax.numpy.asarray, saved["state"].params),
        jax.random.wrap_key_data(jax.numpy.asarray(saved["state"].key)))
    resumed = fit(resumed_state, loader2, x_test2, y_test2, epochs=2,
                  batch_size=32, lr=0.1, log=lambda _m: None,
                  start_epoch=saved["pos"][0], start_offset=saved["pos"][1],
                  input_workers=2, prefetch_depth=2)
    a = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, unbroken.params))
    b = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, resumed.params))
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# NetCDF source through the pipeline
# ---------------------------------------------------------------------------

def test_netcdf_loader_through_worker_pool(tmp_path):
    from pytorch_ddp_mnist_tpu.data.convert import main as convert_main
    from pytorch_ddp_mnist_tpu.data.loader import NetCDFShardLoader

    convert_main(["--synthetic", "128:16", "--out_dir", str(tmp_path)])
    ldr = NetCDFShardLoader(str(tmp_path / "mnist_train_images.nc"),
                            batch_size=32)
    ldr.sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    ldr.sampler.set_epoch(0)
    want = _materialize(ldr)
    got = _materialize(WorkerPool(ShardReader(ldr), 2,
                                  registry=MetricsRegistry()))
    _assert_batches_equal(want, got)
