"""Chaos matrix: REAL SIGKILLs at seeded steps, relaunch, bitwise parity.

The crash-consistency claims of train/ckpt_manager.py are only claims until
a process actually dies mid-run: these tests kill trainer processes with
SIGKILL (no cleanup, no atexit — a real preemption) at a
random-but-seeded step via utils/faultpoints, relaunch with
`--resume <ckpt dir>`, and assert the finished params are byte-identical
to an unbroken run's. The 4-process version drives `scripts/chaos_smoke.py`
(the `make chaos-smoke` front door); the multi-seed soak is `slow`.
"""

import os
import random
import subprocess
import sys

import pytest

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_JAX_V = tuple(int(x) for x in jax.__version__.split(".")[:2])


def _run_cli(args, extra_env=None, timeout=240):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)


def _ckpt_bytes(path):
    with open(path, "rb") as f:
        return f.read()


def test_serial_kill_at_seeded_step_resumes_bitwise(tmp_path):
    """Kill-at-step-k, serial: SIGKILL at a seeded mid-epoch step, relaunch
    with --resume <steps dir>, finish — final checkpoint byte-identical to
    the unbroken run. Then deliberately TRUNCATE the newest checkpoint and
    resume again from an earlier intact one: parity must still hold and
    the relaunch must log the fallback (acceptance criterion #3)."""
    base = ["--limit", "512", "--batch_size", "64", "--lr", "0.1",
            "--cached", "--n_epochs", "3", "--path", str(tmp_path / "data"),
            "--ckpt_every_steps", "2"]
    steps_per_epoch = 8                      # 512 / 64
    rng = random.Random(42)
    kill_step = rng.randrange(2, 2 * steps_per_epoch)  # seeded, mid-run

    golden = tmp_path / "golden.msgpack"
    r = _run_cli(base + ["--checkpoint", str(golden)])
    assert r.returncode == 0, r.stderr

    flaky = tmp_path / "flaky.msgpack"
    r = _run_cli(base + ["--checkpoint", str(flaky)],
                 extra_env={"PDMT_FAULT": f"kill:step={kill_step}"})
    assert r.returncode == -9, (r.returncode, r.stderr[-2000:])
    steps_dir = tmp_path / "flaky.msgpack.steps"
    saved = sorted(p for p in os.listdir(steps_dir) if p.endswith(".json"))
    assert saved, "the killed run left no committed step checkpoints"

    r = _run_cli(base + ["--checkpoint", str(flaky),
                         "--resume", str(steps_dir)])
    assert r.returncode == 0, r.stderr[-3000:]
    assert "[ckpt] resuming from" in r.stderr
    assert _ckpt_bytes(golden) == _ckpt_bytes(flaky)

    # -- torn-newest leg: truncate the newest payload, resume again --------
    newest = sorted(p for p in os.listdir(steps_dir)
                    if p.endswith(".msgpack"))[-1]
    blob = (steps_dir / newest).read_bytes()
    (steps_dir / newest).write_bytes(blob[: len(blob) // 2])
    torn = tmp_path / "torn.msgpack"
    r = _run_cli(base + ["--checkpoint", str(torn),
                         "--resume", str(steps_dir)])
    assert r.returncode == 0, r.stderr[-3000:]
    assert "skipping torn checkpoint" in r.stderr      # the fallback, named
    assert "[ckpt] resuming from" in r.stderr
    assert _ckpt_bytes(golden) == _ckpt_bytes(torn)


def test_pipeline_kill_at_seeded_step_resumes_bitwise(tmp_path):
    """Mid-epoch resume THROUGH the staged input pipeline (ISSUE 12): a
    STREAMING run with decode workers + depth-2 device prefetch live
    (`--input_workers 2 --prefetch_depth 2`) is SIGKILLed at a seeded
    mid-epoch step, resumed from the step-checkpoint directory with the
    pipeline still on, and the finished checkpoint must be byte-identical
    to an UNPIPED golden run — one test pins both the pipeline's
    legacy-loader bitwise parity AND that `iter_from`-level resume holds
    with workers running (skipped batches never gathered, worker threads
    re-seated past the offset)."""
    base = ["--limit", "256", "--batch_size", "32", "--lr", "0.1",
            "--n_epochs", "2", "--path", str(tmp_path / "data"),
            "--ckpt_every_steps", "2"]
    pipe = ["--input_workers", "2", "--prefetch_depth", "2"]
    steps_per_epoch = 8                      # 256 / 32
    kill_step = random.Random(23).randrange(2, 2 * steps_per_epoch - 1)

    golden = tmp_path / "golden.msgpack"     # UNPIPED parity target
    r = _run_cli(base + ["--checkpoint", str(golden)])
    assert r.returncode == 0, r.stderr[-3000:]

    flaky = tmp_path / "flaky.msgpack"
    r = _run_cli(base + pipe + ["--checkpoint", str(flaky)],
                 extra_env={"PDMT_FAULT": f"kill:step={kill_step}"})
    assert r.returncode == -9, (r.returncode, r.stderr[-2000:])
    steps_dir = tmp_path / "flaky.msgpack.steps"
    assert sorted(p for p in os.listdir(steps_dir)
                  if p.endswith(".json")), \
        "the killed piped run left no committed step checkpoints"

    r = _run_cli(base + pipe + ["--checkpoint", str(flaky),
                                "--resume", str(steps_dir)])
    assert r.returncode == 0, r.stderr[-3000:]
    assert "[ckpt] resuming from" in r.stderr
    assert _ckpt_bytes(golden) == _ckpt_bytes(flaky)


def test_int8_kill_resume_drift_bounded(tmp_path):
    """comm=int8 crash/resume coverage (ISSUE 7 satellite): SIGKILL an
    8-fake-device --parallel --ddp_comm int8 run at a seeded mid-run step,
    relaunch with --resume, and pin the finished params against the
    unbroken run with the bounded-drift contract (atol 1e-6 — observed
    0.0: the error-feedback residual rides the step checkpoints
    (`step_N.resid.msgpack`), so the resumed run continues the exact
    quantization-error accounting and parity is in fact bitwise; the pin
    is the documented contract, not the observation)."""
    import numpy as np
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.train.checkpoint import load_checkpoint

    ddp_env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    # --batch_size is PER-CHIP under --parallel: 8 * 8 devices = 64 global
    # -> 8 steps/epoch over the 512-row limit, 16 steps total
    base = ["--limit", "512", "--batch_size", "8", "--lr", "0.1",
            "--parallel", "--wireup_method", "single", "--ddp_comm", "int8",
            "--n_epochs", "2", "--path", str(tmp_path / "data"),
            "--ckpt_every_steps", "2"]
    kill_step = random.Random(13).randrange(2, 14)     # seeded, mid-run

    golden = tmp_path / "golden.msgpack"
    r = _run_cli(base + ["--checkpoint", str(golden)], extra_env=ddp_env)
    assert r.returncode == 0, r.stderr[-3000:]

    flaky = tmp_path / "flaky.msgpack"
    r = _run_cli(base + ["--checkpoint", str(flaky)],
                 extra_env=dict(ddp_env,
                                PDMT_FAULT=f"kill:step={kill_step}"))
    assert r.returncode == -9, (r.returncode, r.stderr[-2000:])
    steps_dir = tmp_path / "flaky.msgpack.steps"
    # the killed run committed residual payloads alongside the params
    assert any(p.endswith(".resid.msgpack") for p in os.listdir(steps_dir))

    r = _run_cli(base + ["--checkpoint", str(flaky),
                         "--resume", str(steps_dir)], extra_env=ddp_env)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "[ckpt] resuming from" in r.stderr
    tmpl = init_mlp(jax.random.key(0))
    want = load_checkpoint(str(golden), tmpl)
    got = load_checkpoint(str(flaky), tmpl)
    worst = max(float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
                for a, b in zip(jax.tree_util.tree_leaves(got),
                                jax.tree_util.tree_leaves(want)))
    assert worst <= 1e-6, worst


@pytest.mark.skipif(_JAX_V < (0, 5),
                    reason="CPU multiprocess collectives need jax >= 0.5")
def test_four_process_kill_chaos_via_smoke_script(tmp_path):
    """THE acceptance run, through the front door: scripts/chaos_smoke.py
    SIGKILLs a seeded rank of a 4-process world at a seeded mid-epoch
    step, reaps the survivors, relaunches with --resume, and asserts
    bitwise parity + telemetry (`check_telemetry --require checkpoint.`)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join("scripts", "chaos_smoke.py"),
         "--workdir", str(tmp_path), "--keep_workdir",
         "--chaos_seed", "7", "--limit", "512"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    if r.returncode == 75:
        pytest.skip("chaos_smoke skipped: no CPU multiprocess collectives")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert '"parity": "bitwise"' in r.stdout
    assert '"telemetry": "validated"' in r.stdout
    # the chaos world really did kill a rank mid-run and leave evidence
    assert (tmp_path / "flaky.msgpack.steps").is_dir()


@pytest.mark.slow
@pytest.mark.skipif(_JAX_V < (0, 5),
                    reason="CPU multiprocess collectives need jax >= 0.5")
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_chaos_soak_multi_seed(tmp_path, seed):
    """The long chaos soak: the same 4-process kill/resume matrix across
    several seeds (different kill rank AND kill step each time). Marked
    slow — tier-1 runs the single-seed smoke above."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join("scripts", "chaos_smoke.py"),
         "--workdir", str(tmp_path), "--chaos_seed", str(seed),
         "--limit", "512"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    if r.returncode == 75:
        pytest.skip("chaos_smoke skipped: no CPU multiprocess collectives")
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
