"""End-to-end serial training slice (the ddp_tutorial_cpu.py capability):
loss decreases, epoch line prints in the reference format, checkpoint
round-trips."""

import re

import jax
import numpy as np

from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images, BatchLoader
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
from pytorch_ddp_mnist_tpu.train import (
    TrainState, fit, make_eval_step, evaluate, save_checkpoint, load_checkpoint)


def _setup(n_train=512, n_test=128):
    train = synthetic_mnist(n_train, seed=0)
    test = synthetic_mnist(n_test, seed=1)
    x_train = normalize_images(train.images)
    x_test = normalize_images(test.images)
    sampler = ShardedSampler(n_train, num_replicas=1, rank=0)
    loader = BatchLoader(x_train, train.labels, sampler, batch_size=64)
    return loader, x_test, test.labels.astype(np.int32)


def test_fit_reduces_loss_and_prints_reference_format():
    loader, x_test, y_test = _setup()
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(42))
    eval_step = make_eval_step()
    _, loss_before, _ = evaluate(eval_step, state.params, x_test, y_test, 64)
    lines = []
    state = fit(state, loader, x_test, y_test, epochs=3, lr=0.05,
                batch_size=64, log=lines.append)
    _, loss_after, acc_after = evaluate(eval_step, state.params, x_test, y_test, 64)
    assert loss_after < loss_before * 0.8
    assert acc_after > 0.5  # synthetic classes are separable
    assert len(lines) == 3
    # Reference epoch line prefix: "Epoch=i, train_loss=…, val_loss=…"
    assert re.match(r"Epoch=0, train_loss=[\d.]+, val_loss=[\d.]+", lines[0])
    # streaming path reports the loader-wait split (SURVEY.md §5.1 capability)
    assert re.search(r"io=[\d.]+s/\d+%", lines[0])


def test_fit_hoists_test_set_to_device_once(monkeypatch):
    """evaluate() must receive device-resident test arrays so no per-epoch
    H2D happens (VERDICT r1 weak #7)."""
    import jax.numpy as jnp
    import pytorch_ddp_mnist_tpu.train.loop as loop_mod
    loader, x_test, y_test = _setup(128, 64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(0))
    seen = []
    real_evaluate = loop_mod.evaluate

    def spy(eval_step, params, x, y, bs):
        seen.append((type(x), type(y)))
        return real_evaluate(eval_step, params, x, y, bs)

    monkeypatch.setattr(loop_mod, "evaluate", spy)
    fit(state, loader, x_test, y_test, epochs=2, lr=0.01, batch_size=64,
        log=lambda s: None)
    assert len(seen) == 2
    for tx, ty in seen:
        assert issubclass(tx, jax.Array) and issubclass(ty, jax.Array)


def test_checkpoint_round_trip(tmp_path):
    params = init_mlp(jax.random.key(7))
    path = str(tmp_path / "model.msgpack")
    save_checkpoint(path, params)
    template = init_mlp(jax.random.key(8))
    restored = load_checkpoint(path, template)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_epoch_hook_called_each_epoch():
    loader, x_test, y_test = _setup(128, 64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(0))
    seen = []
    fit(state, loader, x_test, y_test, epochs=2, lr=0.01, batch_size=64,
        log=lambda s: None, epoch_hook=lambda e, st: seen.append(e))
    assert seen == [0, 1]


def test_evaluate_partial_batch_unbiased():
    """Padded rows must not bias eval metrics (reviewed failure: wrap-padded
    duplicates were averaged in). n=10 with batch 8 -> last batch 2 valid."""
    import jax.numpy as jnp
    from pytorch_ddp_mnist_tpu.ops import cross_entropy
    from pytorch_ddp_mnist_tpu.models import mlp_apply
    params = init_mlp(jax.random.key(3))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(10, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=10).astype(np.int32)
    eval_step = make_eval_step()
    _, mean_loss, acc = evaluate(eval_step, params, x, y, batch_size=8)
    # exact per-sample reference computed in one unbatched pass
    logits = mlp_apply(params, jnp.asarray(x), train=False)
    want_loss = float(cross_entropy(logits, jnp.asarray(y)))
    want_acc = float((np.argmax(np.asarray(logits), 1) == y).mean())
    assert abs(mean_loss - want_loss) < 1e-5
    assert abs(acc - want_acc) < 1e-9


def test_fit_requires_exactly_one_of_lr_or_train_step():
    loader, x_test, y_test = _setup(128, 64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(0))
    import pytest
    with pytest.raises(ValueError, match="exactly one"):
        fit(state, loader, x_test, y_test, epochs=1, batch_size=64)
    with pytest.raises(ValueError, match="exactly one"):
        fit(state, loader, x_test, y_test, epochs=1, batch_size=64,
            lr=0.1, train_step=lambda *a: a)
