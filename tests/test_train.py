"""End-to-end serial training slice (the ddp_tutorial_cpu.py capability):
loss decreases, epoch line prints in the reference format, checkpoint
round-trips."""

import re

import jax
import numpy as np
import pytest

from pytorch_ddp_mnist_tpu.data import synthetic_mnist, normalize_images, BatchLoader
from pytorch_ddp_mnist_tpu.models import init_mlp
from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
from pytorch_ddp_mnist_tpu.train import (
    TrainState, fit, make_eval_step, evaluate, save_checkpoint, load_checkpoint)


def _setup(n_train=512, n_test=128):
    train = synthetic_mnist(n_train, seed=0)
    test = synthetic_mnist(n_test, seed=1)
    x_train = normalize_images(train.images)
    x_test = normalize_images(test.images)
    sampler = ShardedSampler(n_train, num_replicas=1, rank=0)
    loader = BatchLoader(x_train, train.labels, sampler, batch_size=64)
    return loader, x_test, test.labels.astype(np.int32)


def test_fit_reduces_loss_and_prints_reference_format():
    loader, x_test, y_test = _setup()
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(42))
    eval_step = make_eval_step()
    _, loss_before, _ = evaluate(eval_step, state.params, x_test, y_test, 64)
    lines = []
    state = fit(state, loader, x_test, y_test, epochs=3, lr=0.05,
                batch_size=64, log=lines.append)
    _, loss_after, acc_after = evaluate(eval_step, state.params, x_test, y_test, 64)
    assert loss_after < loss_before * 0.8
    assert acc_after > 0.5  # synthetic classes are separable
    assert len(lines) == 3
    # Reference epoch line prefix: "Epoch=i, train_loss=…, val_loss=…"
    assert re.match(r"Epoch=0, train_loss=[\d.]+, val_loss=[\d.]+", lines[0])
    # streaming path reports the loader-wait split (SURVEY.md §5.1 capability)
    assert re.search(r"io=[\d.]+s/\d+%", lines[0])


def test_fit_hoists_test_set_to_device_once(monkeypatch):
    """evaluate() must receive device-resident test arrays so no per-epoch
    H2D happens (VERDICT r1 weak #7)."""
    import jax.numpy as jnp
    import pytorch_ddp_mnist_tpu.train.loop as loop_mod
    loader, x_test, y_test = _setup(128, 64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(0))
    seen = []
    real_evaluate = loop_mod.evaluate

    def spy(eval_step, params, x, y, bs, perm=None):
        seen.append((type(x), type(y)))
        return real_evaluate(eval_step, params, x, y, bs, perm=perm)

    monkeypatch.setattr(loop_mod, "evaluate", spy)
    fit(state, loader, x_test, y_test, epochs=2, lr=0.01, batch_size=64,
        log=lambda s: None)
    assert len(seen) == 2
    for tx, ty in seen:
        assert issubclass(tx, jax.Array) and issubclass(ty, jax.Array)


def test_checkpoint_round_trip(tmp_path):
    params = init_mlp(jax.random.key(7))
    path = str(tmp_path / "model.msgpack")
    save_checkpoint(path, params)
    template = init_mlp(jax.random.key(8))
    restored = load_checkpoint(path, template)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_torch_checkpoint_round_trip(tmp_path):
    """A .pt path writes/reads the reference's torch state_dict format."""
    pytest.importorskip("torch")
    params = init_mlp(jax.random.key(7))
    path = str(tmp_path / "model.pt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path, init_mlp(jax.random.key(8)))
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_torch_checkpoint_loads_into_reference_model(tmp_path):
    """The .pt file we save must be consumable by the reference consumer:
    `model.load_state_dict(torch.load('model.pt'))` on the reference's own
    nn.Sequential graph (ddp_tutorial_cpu.py:45-51), strict=True, with
    matching forward logits."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn
    from pytorch_ddp_mnist_tpu.models import mlp_apply

    params = init_mlp(jax.random.key(11))
    path = str(tmp_path / "model.pt")
    save_checkpoint(path, params)

    model = nn.Sequential(
        nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
        nn.Linear(128, 128), nn.ReLU(),
        nn.Linear(128, 10, bias=False))
    model.load_state_dict(torch.load(path, weights_only=True), strict=True)
    model.eval()

    x = np.random.default_rng(0).normal(size=(32, 784)).astype(np.float32)
    with torch.no_grad():
        theirs = model(torch.from_numpy(x)).numpy()
    ours = np.asarray(mlp_apply(params, jax.numpy.asarray(x), train=False))
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_resume_from_reference_produced_model_pt(tmp_path):
    """The reverse direction: a model.pt written the reference's way
    (torch.save(model.state_dict(), ...), ddp_tutorial_multi_gpu.py:143-144)
    seeds our params pytree."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    torch.manual_seed(3)
    model = nn.Sequential(
        nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
        nn.Linear(128, 128), nn.ReLU(),
        nn.Linear(128, 10, bias=False))
    path = str(tmp_path / "model.pt")
    torch.save(model.state_dict(), path)

    params = load_checkpoint(path, init_mlp(jax.random.key(0)))
    sd = model.state_dict()
    np.testing.assert_allclose(np.asarray(params["fc1"]["w"]),
                               sd["0.weight"].numpy().T)
    np.testing.assert_allclose(np.asarray(params["fc2"]["b"]),
                               sd["3.bias"].numpy())
    np.testing.assert_allclose(np.asarray(params["fc3"]["w"]),
                               sd["5.weight"].numpy().T)
    assert "b" not in params["fc3"]  # output layer is bias-free


def test_live_loss_polls_ready_values_without_sync():
    """The async per-step loss display: shows the newest COMPLETED value,
    never touches a pending one (no forced device sync), no-ops on bars
    without postfix support."""
    import types
    import jax.numpy as jnp
    from pytorch_ddp_mnist_tpu.train.loop import _LiveLoss

    msgs = []
    ll = _LiveLoss(types.SimpleNamespace(set_postfix_str=msgs.append),
                   interval=0.0)
    losses = [jnp.float32(0.5)]
    ll.poll(losses)
    assert msgs and msgs[-1].endswith("@0") and "0.5" in msgs[-1]

    class Pending:
        def is_ready(self):
            return False

        def __float__(self):
            raise AssertionError("fetched a value that was not ready")

    losses.append(Pending())
    ll.poll(losses)                      # nothing newly ready -> no update
    assert len(msgs) == 1
    losses.append(jnp.float32(0.25))
    ll.poll(losses)                      # newest ready wins, pending skipped
    assert len(msgs) == 2 and msgs[-1].endswith("@2")
    _LiveLoss(object(), interval=0.0).poll(losses)   # no postfix API: no-op


def test_torch_checkpoint_ddp_wrapped_module_prefix_loads(tmp_path):
    """A still-DDP-wrapped save ('module.'-prefixed keys — the reference
    always unwraps first, ddp_tutorial_multi_gpu.py:118, but a user's own
    save may not) loads by stripping the uniform prefix."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    torch.manual_seed(4)
    model = nn.Sequential(
        nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
        nn.Linear(128, 128), nn.ReLU(),
        nn.Linear(128, 10, bias=False))
    wrapped = {f"module.{k}": v for k, v in model.state_dict().items()}
    path = str(tmp_path / "model.pt")
    torch.save(wrapped, path)

    params = load_checkpoint(path, init_mlp(jax.random.key(0)))
    np.testing.assert_allclose(np.asarray(params["fc1"]["w"]),
                               model.state_dict()["0.weight"].numpy().T)


def test_torch_checkpoint_unknown_layout_names_expected_keys(tmp_path):
    """A state_dict with non-reference key names must fail with a ValueError
    listing the expected reference keys, not a bare KeyError."""
    torch = pytest.importorskip("torch")
    path = str(tmp_path / "model.pt")
    torch.save({"encoder.weight": torch.zeros(2, 2)}, path)
    with pytest.raises(ValueError, match=r"0\.weight.*expected"):
        load_checkpoint(path, init_mlp(jax.random.key(0)))


def test_torch_checkpoint_shape_mismatch_fails_at_load(tmp_path):
    """A wrong-shape model.pt (e.g. hidden=64 variant) must fail AT LOAD with
    a named error, not later as an opaque XLA shape error."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    model = nn.Sequential(
        nn.Linear(784, 64), nn.ReLU(), nn.Dropout(0.2),
        nn.Linear(64, 64), nn.ReLU(), nn.Linear(64, 10, bias=False))
    path = str(tmp_path / "model.pt")
    torch.save(model.state_dict(), path)

    with pytest.raises(ValueError, match=r"fc1.*shape"):
        load_checkpoint(path, init_mlp(jax.random.key(0)))


def test_torch_checkpoint_structure_mismatch_fails_at_load(tmp_path):
    """A state_dict whose layer structure differs (output layer WITH bias)
    must fail at load with a structure error, not misattribute shapes."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    model = nn.Sequential(
        nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
        nn.Linear(128, 128), nn.ReLU(), nn.Linear(128, 10, bias=True))
    path = str(tmp_path / "model.pt")
    torch.save(model.state_dict(), path)

    with pytest.raises(ValueError, match="structure"):
        load_checkpoint(path, init_mlp(jax.random.key(0)))


def test_epoch_hook_called_each_epoch():
    loader, x_test, y_test = _setup(128, 64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(0))
    seen = []
    fit(state, loader, x_test, y_test, epochs=2, lr=0.01, batch_size=64,
        log=lambda s: None, epoch_hook=lambda e, st: seen.append(e))
    assert seen == [0, 1]


def test_evaluate_partial_batch_unbiased():
    """Padded rows must not bias eval metrics (reviewed failure: wrap-padded
    duplicates were averaged in). n=10 with batch 8 -> last batch 2 valid."""
    import jax.numpy as jnp
    from pytorch_ddp_mnist_tpu.ops import cross_entropy
    from pytorch_ddp_mnist_tpu.models import mlp_apply
    params = init_mlp(jax.random.key(3))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(10, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=10).astype(np.int32)
    eval_step = make_eval_step()
    _, mean_loss, acc = evaluate(eval_step, params, x, y, batch_size=8)
    # exact per-sample reference computed in one unbatched pass
    logits = mlp_apply(params, jnp.asarray(x), train=False)
    want_loss = float(cross_entropy(logits, jnp.asarray(y)))
    want_acc = float((np.argmax(np.asarray(logits), 1) == y).mean())
    assert abs(mean_loss - want_loss) < 1e-5
    assert abs(acc - want_acc) < 1e-9


def test_fit_requires_exactly_one_of_lr_or_train_step():
    loader, x_test, y_test = _setup(128, 64)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(0))
    import pytest
    with pytest.raises(ValueError, match="exactly one"):
        fit(state, loader, x_test, y_test, epochs=1, batch_size=64)
    with pytest.raises(ValueError, match="exactly one"):
        fit(state, loader, x_test, y_test, epochs=1, batch_size=64,
            lr=0.1, train_step=lambda *a: a)
