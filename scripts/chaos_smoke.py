#!/usr/bin/env python
"""Chaos smoke: kill a 4-process training run at a seeded step, resume it,
and prove bitwise parity with the unbroken run.

    python scripts/chaos_smoke.py [--workdir DIR] [--chaos_seed N]
                                  [--world 4] [--epochs 3] ...

The front door of docs/ROBUSTNESS.md (`make chaos-smoke`). One invocation
runs the whole chaos matrix on fake CPU devices:

  1. BASELINE — an unbroken `--parallel --cached` world with
     `--ckpt_every_steps`, producing the golden final checkpoint;
  2. CHAOS    — the same world with `PDMT_FAULT=kill:rank=R:step=K`
     (R and K drawn from --chaos_seed: random-but-seeded, reproducible):
     rank R SIGKILLs itself mid-epoch at the first step boundary >= K,
     the survivors are reaped (a gang scheduler killing the job), and the
     step-checkpoint directory is left exactly as the crash left it;
  3. RESUME   — a fresh world relaunched with `--resume <ckpt dir>`: every
     rank restores the newest INTACT checkpoint (falling back past a torn
     one if the kill interrupted a save) and finishes the run;
  4. VERDICT  — the resumed final checkpoint must be BYTE-IDENTICAL to the
     baseline's, and the resumed run's telemetry must schema-validate and
     carry the checkpoint.* metrics (`check_telemetry --require checkpoint.`).
  5. PIPELINE LEG (serial) — the same kill/resume matrix THROUGH the
     staged input pipeline (docs/DATA.md): a streaming run with
     `--input_workers 2 --prefetch_depth 2` is SIGKILLed at a seeded
     mid-epoch step with decode workers live, resumed from the step-ckpt
     directory with the pipeline still on, and its final checkpoint must
     be BYTE-IDENTICAL to an UNPIPED golden run — mid-epoch resume and
     the piped-vs-unpiped parity pin, in one leg;
  6. ELASTIC LEG — the shrink/grow cycle (docs/ROBUSTNESS.md §Elastic
     training), delegated to scripts/elastic_smoke.py: one rank killed
     mid-run, survivors rescue + re-wire into the smaller world under the
     next generation, then the world grows back — with loss-curve
     continuity and the post-reshape collective schedule asserted.

Exit codes: 0 = parity held; 1 = any phase failed (with the failing rank's
output on stderr); 75 = skipped, this jax has no CPU multiprocess
collectives (same convention as measure_hw.sh's skipped phase).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(rank: int, port: int, argv, world: int, extra_env=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(world),
        "RANK": str(rank),
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train", *argv],
        cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _run_world(argv, world: int, timeout: float, extra_env=None):
    """Run a world to completion; returns [(rc, out, err)] per rank."""
    procs = [_spawn(r, _port_box["port"], argv, world, extra_env)
             for r in range(world)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, err = p.communicate()
            outs.append((None, out, err))
    for p in procs:
        if p.poll() is None:
            p.kill()
        p.wait()
    return outs


_port_box = {"port": 0}


def _run_chaos_world(argv, world: int, kill_rank: int, timeout: float,
                     fault: str):
    """Run a world expecting rank `kill_rank` to die by SIGKILL; once it
    does, reap the survivors (the gang-scheduler model: one task dead ==
    job dead). Returns the killed rank's returncode (-9 expected)."""
    procs = [_spawn(r, _port_box["port"], argv, world,
                    {"PDMT_FAULT": fault})
             for r in range(world)]
    deadline = time.monotonic() + timeout
    victim = procs[kill_rank]
    while victim.poll() is None and time.monotonic() < deadline:
        time.sleep(0.25)
    rc = victim.poll()
    # reap the survivors: they are blocked in a collective whose peer died
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        p.communicate()
    return rc


def _final_params(path: str):
    with open(path, "rb") as f:
        return f.read()


def _run_serial(argv, timeout: float, extra_env=None):
    """One serial (no-rendezvous) trainer process — the pipeline leg's
    runner. Returns (rc, out, err)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train", *argv],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
        return r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        return None, e.stdout or "", e.stderr or ""


def _sweep_stray_tmp(steps_dir: str):
    """Sweep the dead writer's orphan `.tmp.<pid>` strays out of the step
    directory and return their names.

    A kill that lands BETWEEN a save's payload-tmp write and its manifest
    rename (pinned rescue saves included) leaves an uncommitted
    `*.tmp.<pid>` stray. The manager's own rotation sweeps those on the
    NEXT save from a live writer — but this leg's resumed run may finish
    without rank 0 rotating (kill near the end of the run), and the stray
    then outlives the smoke, reading as a half-written checkpoint to
    whoever inspects the directory. The smoke models the operator here:
    sweep before resume, and assert nothing `.tmp.` survives the leg."""
    swept = []
    try:
        names = os.listdir(steps_dir)
    except OSError:
        return swept
    for name in names:
        if ".tmp." in name:
            try:
                os.unlink(os.path.join(steps_dir, name))
                swept.append(name)
            except OSError:
                pass
    return swept


def _pipeline_leg(work: str, chaos_seed: int, timeout: float):
    """Kill/resume THROUGH the input pipeline (step 5 of the module
    docstring). Returns (ok, detail)."""
    limit, batch, epochs, every = 256, 32, 2, 2
    steps_per_epoch = -(-limit // batch)
    total = steps_per_epoch * epochs
    kill_step = random.Random(chaos_seed + 1).randrange(
        max(1, every), total - 1)
    golden = os.path.join(work, "pipe_golden.msgpack")
    flaky = os.path.join(work, "pipe_flaky.msgpack")
    base = ["--n_epochs", str(epochs), "--limit", str(limit),
            "--batch_size", str(batch), "--lr", "0.1",
            "--path", os.path.join(work, "data"),
            "--ckpt_every_steps", str(every)]
    pipe = ["--input_workers", "2", "--prefetch_depth", "2"]

    # golden: UNPIPED — the parity target is the legacy synchronous path
    rc, out, err = _run_serial(base + ["--checkpoint", golden], timeout)
    if rc != 0:
        return False, f"pipeline golden rc={rc}\n{out}\n{err}"
    # chaos: piped run SIGKILLed mid-epoch with decode workers live
    rc, out, err = _run_serial(
        base + pipe + ["--checkpoint", flaky], timeout,
        extra_env={"PDMT_FAULT": f"kill:step={kill_step}"})
    if rc != -9:
        return False, (f"pipeline chaos rc={rc}, expected SIGKILL (-9)"
                       f"\n{out}\n{err}")
    steps_dir = flaky + ".steps"
    if not os.path.isdir(steps_dir) or not os.listdir(steps_dir):
        return False, f"no step checkpoints under {steps_dir}"
    # the kill may have landed between a save's payload-tmp and its
    # manifest rename: sweep the dead writer's orphan strays so the
    # directory the resume sees (and the one the smoke leaves behind)
    # holds only committed checkpoints
    swept = _sweep_stray_tmp(steps_dir)
    # resume: pipeline still on, restores mid-epoch and finishes
    rc, out, err = _run_serial(
        base + pipe + ["--checkpoint", flaky, "--resume", steps_dir],
        timeout)
    if rc != 0:
        return False, f"pipeline resume rc={rc}\n{out}\n{err}"
    if "[ckpt] resuming from" not in err:
        return False, f"pipeline resume printed no restore line\n{err}"
    if _final_params(golden) != _final_params(flaky):
        return False, ("piped kill/resume final checkpoint differs from "
                       "the UNPIPED golden run")
    stray = [n for n in os.listdir(steps_dir) if ".tmp." in n]
    if stray:
        return False, (f"orphan tmp strays survived the pipeline leg: "
                       f"{stray}")
    return True, {"kill_step": kill_step, "steps_per_epoch": steps_per_epoch,
                  "swept_strays": swept}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="4-process kill/resume chaos parity smoke")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--chaos_seed", type=int, default=0,
                    help="seeds the (kill rank, kill step) draw")
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--limit", type=int, default=1024)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--ckpt_every_steps", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=420.0,
                    help="per-world wall bound (seconds)")
    ap.add_argument("--keep_workdir", action="store_true")
    a = ap.parse_args(argv)

    # CPU multiprocess collectives need jax >= 0.5 (same gate as
    # tests/test_multiprocess.py): absent capability = skip, not failure.
    # A --world 1 run has no cross-process collective and stays valid
    # everywhere (the driver-mechanics fallback for older jaxlibs).
    import jax
    if (a.world > 1
            and tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5)):
        print("chaos_smoke: SKIP — this jaxlib has no CPU multiprocess "
              "collectives (needs jax >= 0.5)", file=sys.stderr)
        return 75

    work = a.workdir or tempfile.mkdtemp(prefix="pdmt_chaos_")
    os.makedirs(work, exist_ok=True)
    golden = os.path.join(work, "golden.msgpack")
    flaky = os.path.join(work, "flaky.msgpack")
    steps_dir = flaky + ".steps"
    telemetry = os.path.join(work, "telemetry")

    # the per-rank steps per epoch: ceil(limit / (batch * world)) — kill
    # somewhere strictly inside the run, never in the final epoch's tail
    # (a kill after the last checkpoint would still pass, but killing
    # mid-epoch is the property this smoke exists to exercise)
    steps_per_epoch = -(-a.limit // (a.batch_size * a.world))
    total = steps_per_epoch * a.epochs
    rng = random.Random(a.chaos_seed)
    kill_rank = rng.randrange(a.world)
    # the draw needs a checkpoint BEFORE the kill (lo >= first save) and
    # the kill strictly inside the run; refuse impossible geometry by name
    # rather than crashing on an empty randrange
    lo = max(1, a.ckpt_every_steps)
    if lo >= total:
        print(f"chaos_smoke: ERROR — ckpt_every_steps={a.ckpt_every_steps} "
              f">= the run's {total} total steps ({steps_per_epoch}/epoch x "
              f"{a.epochs} epochs): no step checkpoint would ever commit "
              f"before the kill. Lower --ckpt_every_steps or raise "
              f"--epochs/--limit.", file=sys.stderr)
        return 2
    kill_step = rng.randrange(lo, max(lo + 1, total - steps_per_epoch))
    fault = f"kill:rank={kill_rank}:step={kill_step}"
    print(f"chaos_smoke: world={a.world} steps/epoch={steps_per_epoch} "
          f"chaos_seed={a.chaos_seed} -> {fault}")

    base = ["--parallel", "--cached", "--wireup_method", "env",
            "--n_epochs", str(a.epochs), "--limit", str(a.limit),
            "--batch_size", str(a.batch_size), "--lr", "0.1",
            "--path", os.path.join(work, "data"),
            "--ckpt_every_steps", str(a.ckpt_every_steps)]

    def fail(phase, outs):
        print(f"chaos_smoke: FAIL in {phase}", file=sys.stderr)
        for rank, (rc, out, err) in enumerate(outs):
            print(f"--- rank {rank} rc={rc}\n{out}\n{err}",
                  file=sys.stderr)
        return 1

    # 1. baseline
    _port_box["port"] = _free_port()
    outs = _run_world(base + ["--checkpoint", golden], a.world, a.timeout)
    if any(rc != 0 for rc, _, _ in outs):
        return fail("baseline", outs)

    # 2. chaos: seeded SIGKILL mid-run
    _port_box["port"] = _free_port()
    rc = _run_chaos_world(base + ["--checkpoint", flaky], a.world,
                          kill_rank, a.timeout, fault)
    if rc != -9:
        print(f"chaos_smoke: FAIL — killed rank exited rc={rc}, "
              f"expected SIGKILL (-9)", file=sys.stderr)
        return 1
    if not os.path.isdir(steps_dir) or not os.listdir(steps_dir):
        print(f"chaos_smoke: FAIL — no step checkpoints under {steps_dir}",
              file=sys.stderr)
        return 1

    # 3. resume from the crash-consistent directory, telemetry on
    _port_box["port"] = _free_port()
    outs = _run_world(base + ["--checkpoint", flaky,
                              "--resume", steps_dir,
                              "--telemetry", telemetry],
                      a.world, a.timeout)
    if any(rc != 0 for rc, _, _ in outs):
        return fail("resume", outs)
    if "[ckpt] resuming from" not in outs[0][2]:
        return fail("resume (no restore line on rank 0)", outs)

    # 4a. bitwise parity of the final checkpoints
    if _final_params(golden) != _final_params(flaky):
        print("chaos_smoke: FAIL — resumed final checkpoint differs from "
              "the unbroken baseline", file=sys.stderr)
        return 1

    # 4b. telemetry schema + checkpoint.* metric gate
    check = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_telemetry.py"),
         "--require", "checkpoint.", telemetry],
        capture_output=True, text=True)
    if check.returncode != 0:
        print(f"chaos_smoke: FAIL — telemetry gate:\n{check.stdout}"
              f"\n{check.stderr}", file=sys.stderr)
        return 1

    # 5. the serial pipeline leg: kill/resume with decode workers live,
    # parity against an UNPIPED golden (mid-epoch resume THROUGH the
    # staged input pipeline — docs/DATA.md)
    ok, detail = _pipeline_leg(work, a.chaos_seed, a.timeout)
    if not ok:
        print(f"chaos_smoke: FAIL in pipeline leg — {detail}",
              file=sys.stderr)
        return 1

    # 6. the elastic shrink/grow leg (docs/ROBUSTNESS.md §Elastic
    # training): one rank killed mid-run, the survivors rescue-checkpoint
    # and re-wire into the smaller world, then the world grows back —
    # loss-curve continuity and the post-reshape collective schedule are
    # asserted by scripts/elastic_smoke.py (its own world-1 fallback runs
    # the reshape math + serial kill/resume cycle when this jaxlib has no
    # CPU multiprocess collectives).
    elastic = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "elastic_smoke.py"),
         "--workdir", os.path.join(work, "elastic"),
         "--world", str(min(a.world, 2))],
        capture_output=True, text=True)
    if elastic.returncode == 75:
        # re-run the driver-mechanics fallback explicitly rather than
        # silently skipping the leg
        elastic = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "elastic_smoke.py"),
             "--workdir", os.path.join(work, "elastic1"), "--world", "1"],
            capture_output=True, text=True)
    if elastic.returncode != 0:
        print(f"chaos_smoke: FAIL in elastic leg —\n{elastic.stdout}"
              f"\n{elastic.stderr}", file=sys.stderr)
        return 1

    print(json.dumps({
        "chaos_smoke": "ok", "world": a.world, "chaos_seed": a.chaos_seed,
        "kill_rank": kill_rank, "kill_step": kill_step,
        "steps_per_epoch": steps_per_epoch,
        "parity": "bitwise", "telemetry": "validated",
        "pipeline_leg": {"parity": "bitwise", **detail},
        "elastic_leg": "ok",
    }))
    if not a.keep_workdir and a.workdir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
