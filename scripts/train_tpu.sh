#!/bin/bash
# Flagship data-parallel trainer — the reference train_multi_gpu.sh analog
# (/root/reference/train_multi_gpu.sh:3: torch.distributed.launch
# --nproc_per_node=8, NCCL, 10 epochs). On TPU one process drives all local
# chips via the SPMD mesh; no per-rank process spawn is needed on a single
# host. For a multi-host pod, run this once per host under your scheduler —
# wireup (SLURM/OpenMPI/MPICH/env) is picked up from the environment.
# --kernel auto picks the fused Pallas step on TPU backends (the fastest
# measured variant, docs/PERF.md); trailing "$@" still overrides any flag.
set -e
cd "$(dirname "$0")/.."
python -m pytorch_ddp_mnist_tpu.cli.train --parallel --n_epochs 10 \
    --kernel auto "$@"
