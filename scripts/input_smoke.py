#!/usr/bin/env python
"""Input-pipeline smoke (docs/DATA.md): drive a REAL telemetry-instrumented
training run through the staged input pipeline — background decode workers
+ depth-K device prefetch over a synthetic source — with the runtime
sanitizers armed, then gate the emitted trace.

    JAX_PLATFORMS=cpu python scripts/input_smoke.py       # = make input-smoke

What it pins:

  * `sanitize.no_host_sync`: the pipeline may add worker threads but ZERO
    consumer-side host syncs — zero block_until_ready calls and the PR 10
    EPOCH-granular fetch budget (<= 6 fetches/epoch) hold with workers
    live (the ISSUE 12 contract);
  * `sanitize.lock_trace`: every lock the worker pool creates (plan lock,
    reorder-buffer condition, slot semaphore) records its acquisition
    order — any observed order cycle fails the smoke (LOCK002's runtime
    confirmation over the new threads);
  * the trace round trip: `scripts/check_telemetry.py --require data.`
    must pass on the run's JSONL — schema + span structure valid AND the
    `data.*` pipeline metrics (queue depth gauge, batch-wait histogram)
    present in the registry snapshot;
  * `trace report --data` renders (the data_wait-share attribution view
    exists for the run), via the same in-process analysis module.

Prints one JSON line on success; exit 1 with the failing contract on
violation. Pure CPU, seconds of wall time — wired into `make check`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

# runnable from anywhere: the repo root (this script's parent's parent)
# fronts sys.path so the package imports without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np

    from pytorch_ddp_mnist_tpu import telemetry
    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.pipeline import SyntheticSource
    from pytorch_ddp_mnist_tpu.statics import sanitize
    from pytorch_ddp_mnist_tpu.telemetry import analysis
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    epochs, workers, depth = 2, 2, 2
    out_dir = tempfile.mkdtemp(prefix="pdmt_input_smoke_")
    out = {"telemetry": out_dir, "epochs": epochs, "workers": workers,
           "prefetch_depth": depth}
    test = synthetic_mnist(64, seed=1)
    src = SyntheticSource(12, 32, latency_s=0.002, seed=0)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    telemetry.enable(out_dir, process_index=0)
    try:
        with sanitize.lock_trace() as locks:
            with sanitize.no_host_sync(max_fetches=epochs * 6) as sync:
                fit(state, src, normalize_images(test.images),
                    test.labels.astype(np.int32), epochs=epochs,
                    batch_size=32, lr=0.1, log=lambda _m: None,
                    input_workers=workers, prefetch_depth=depth)
        out["lock_edges"] = len(locks.edges())
        out["lock_cycles"] = 0
        out["fetches"] = sync.fetches
        out["block_until_ready"] = sync.block_until_ready_calls
    except sanitize.SanitizerError as e:
        print(f"input_smoke: FAIL — {e}", file=sys.stderr)
        return 1
    finally:
        # the data.* registry metrics must land in the trace's final
        # snapshot record for the --require gate below
        telemetry.get_tracer().snapshot(telemetry.get_registry())
        telemetry.disable()

    check = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "check_telemetry.py"),
         "--require", "data.", out_dir],
        capture_output=True, text=True)
    if check.returncode != 0:
        print(f"input_smoke: FAIL — telemetry gate:\n{check.stdout}"
              f"\n{check.stderr}", file=sys.stderr)
        return 1
    out["telemetry_gate"] = "validated"

    rep = analysis.data_report(analysis.trace_files(out_dir))
    if rep["epochs"] != epochs:
        print(f"input_smoke: FAIL — data report attributed "
              f"{rep['epochs']}/{epochs} epochs", file=sys.stderr)
        return 1
    out["data_wait_share_p95"] = round(rep["share"]["p95"], 4)
    out["ok"] = True
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
