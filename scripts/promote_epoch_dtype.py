"""Promote the single-chip epoch kernel's matmul dtype to bfloat16 — IFF
the hardware evidence clears the same two-part gate that promoted rbg in
round 2 (docs/PERF.md):

  1. WIN: the bf16 epoch-kernel row must beat the f32 epoch-kernel row in
     the SAME variant-matrix sweep (one window, one chip — no cross-session
     number mixing);
  2. SEMANTICS: a 10-epoch training run at each dtype must reach test
     accuracy within --acc_tol (default 1 point) — bf16 matmuls change
     rounding, never the training outcome, or they don't ship as a default.

On success writes bench_calibration.json, which `bench.py --dtype auto`
(the flagless default) reads to resolve the epoch kernel's dtype — so the
driver's flagless run only ever changes behavior through a
hardware-verified, committed artifact. Run on real TPU hardware (the
measurement window queue, scripts/measure_hw.sh, runs it after the matrix).

Usage: python scripts/promote_epoch_dtype.py --matrix bench_matrix_r04.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# EXACT headline labels (tests pin them against bench_matrix.VARIANTS): a
# prefix match would also catch the in-kernel-threefry or superstep rows
# and make the gate baseline depend on artifact ordering.
F32_LABEL = "f32 / whole-epoch kernel, uint8 streaming (single-chip headline)"
BF16_LABEL = "bf16-matmul / whole-epoch kernel, uint8 streaming"


def check_win(rows):
    """Stage 1 of the gate, matrix-only: (won?, reason, f32_value,
    bf16_value). Runs BEFORE the accuracy measurements so a losing bf16 row
    (the common case) costs zero extra hardware-window time."""
    by_label = {r["label"]: r for r in rows}
    f32, bf16 = by_label.get(F32_LABEL), by_label.get(BF16_LABEL)
    if f32 is None or bf16 is None:
        return False, "matrix is missing an epoch-kernel row", None, None
    if f32["value"] is None or bf16["value"] is None:
        return False, "an epoch-kernel row has no measured value", None, None
    if bf16["value"] <= f32["value"]:
        return False, (f"bf16 does not win: {bf16['value']:,.0f} <= "
                       f"{f32['value']:,.0f} img/s/chip"), None, None
    return True, (f"bf16 wins {bf16['value']:,.0f} vs {f32['value']:,.0f} "
                  f"img/s/chip"), f32["value"], bf16["value"]


def decide(rows, acc_f32: float, acc_bf16: float, acc_tol: float):
    """The full gate: (promote?, reason). Separated from I/O so CI can pin
    every branch."""
    won, reason, _, _ = check_win(rows)
    if not won:
        return False, reason
    if abs(acc_f32 - acc_bf16) > acc_tol:
        return False, (f"accuracy parity failed: f32 {acc_f32:.4f} vs bf16 "
                       f"{acc_bf16:.4f} (tol {acc_tol})")
    return True, (f"{reason} with accuracy parity "
                  f"({acc_f32:.4f}/{acc_bf16:.4f})")


def measure_accuracy(dtype: str, epochs: int) -> float:
    """Final test accuracy of an `epochs`-epoch single-chip epoch-kernel
    training run (synthetic MNIST, the bench workload's data) at `dtype`."""
    import numpy as np
    import jax

    from pytorch_ddp_mnist_tpu.data import synthetic_mnist
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train.loop import evaluate, make_eval_step
    from pytorch_ddp_mnist_tpu.train.scan import (epoch_batch_indices,
                                                  make_run_fn,
                                                  resident_images)

    train = synthetic_mnist(60000, seed=0)
    test = synthetic_mnist(10000, seed=1)
    x_all = jax.device_put(resident_images(train.images))
    y_all = jax.device_put(train.labels.astype(np.int32))
    sampler = ShardedSampler(60000, num_replicas=1, rank=0, seed=42)
    idxs = []
    for e in range(epochs):
        sampler.set_epoch(e)
        idxs.append(epoch_batch_indices(sampler, 128))
    run = make_run_fn(0.01, dtype=dtype, kernel="pallas_epoch")
    params, _, losses = run(init_mlp(jax.random.key(0)), jax.random.key(1),
                            x_all, y_all, jax.device_put(np.stack(idxs)))
    assert np.isfinite(np.asarray(losses)).all()
    from pytorch_ddp_mnist_tpu.data import normalize_images
    val = evaluate(make_eval_step(), params,
                   jax.numpy.asarray(normalize_images(test.images)),
                   jax.numpy.asarray(test.labels.astype(np.int32)), 128)
    return float(val.accuracy)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--matrix", required=True,
                   help="variant-matrix artifact (bench_matrix.py --out)")
    p.add_argument("--epochs", type=int, default=10,
                   help="epochs per accuracy-parity run")
    p.add_argument("--acc_tol", type=float, default=0.01)
    p.add_argument("--out", default=str(
        pathlib.Path(__file__).resolve().parent.parent
        / "bench_calibration.json"))
    a = p.parse_args(argv)

    with open(a.matrix) as f:
        artifact = json.load(f)

    # Stage 1 (free): the matrix WIN condition — no hardware time is spent
    # on accuracy runs unless bf16 actually won the sweep.
    won, reason, _, _ = check_win(artifact["variants"])
    if not won:
        print(f"promote_epoch_dtype: {reason}", file=sys.stderr)
        return 1

    from pytorch_ddp_mnist_tpu.parallel.wireup import on_tpu_backend
    if not on_tpu_backend():
        print("promote_epoch_dtype: not on a TPU backend; the gate needs "
              "real hardware", file=sys.stderr)
        return 1
    acc_f32 = measure_accuracy("float32", a.epochs)
    acc_bf16 = measure_accuracy("bfloat16", a.epochs)
    promote, reason = decide(artifact["variants"], acc_f32, acc_bf16,
                             a.acc_tol)
    print(f"promote_epoch_dtype: {reason}", file=sys.stderr)
    if not promote:
        return 1
    with open(a.out, "w") as f:
        json.dump({
            "epoch_kernel_dtype": "bfloat16",
            "evidence": {
                "matrix": a.matrix,
                "matrix_timestamp": artifact.get("timestamp"),
                "acc_f32": round(acc_f32, 4),
                "acc_bf16": round(acc_bf16, 4),
                "epochs": a.epochs,
                "reason": reason,
            },
        }, f, indent=1)
        f.write("\n")
    print(f"promote_epoch_dtype: wrote {a.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
