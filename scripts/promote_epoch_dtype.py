"""Promote the single-chip epoch kernel's flagless configuration — matmul
dtype and/or grid superstep — IFF the hardware evidence clears the gate
that promoted rbg in round 2 (docs/PERF.md):

  1. WIN: the candidate row must beat the f32/superstep-1 baseline row in
     the SAME variant-matrix sweep (one window, one chip — no cross-session
     number mixing). Candidates = the epoch-kernel matrix rows: bf16-matmul
     at K=1, f32 superstep K in {2, 4, 8}, and bf16-matmul at K=8 (see
     CANDIDATES below).
  2. SEMANTICS: superstep is bitwise-identical math by construction (CI +
     Mosaic tests pin K==1 equality), so it needs no extra run. bf16
     matmuls change rounding, so a bf16 winner additionally needs a
     10-epoch training run per dtype reaching test accuracy within
     --acc_tol (default 1 point) — they change rounding, never the
     training outcome, or they don't ship as a default.

On success writes bench_calibration.json, which `bench.py`'s flagless
defaults (`--dtype auto`, `--superstep 0`=auto) read to resolve the
single-chip epoch kernel's configuration — the driver's flagless run only
ever changes behavior through a hardware-verified, committed artifact.
Run on real TPU hardware (scripts/measure_hw.sh phase 1b).

Usage: python scripts/promote_epoch_dtype.py --matrix bench_matrix_r04.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# EXACT labels (tests pin them against bench_matrix.VARIANTS): a prefix
# match would also catch the in-kernel-threefry row and make the gate
# baseline depend on artifact ordering.
F32_LABEL = "f32 / whole-epoch kernel, uint8 streaming (single-chip headline)"
BF16_LABEL = "bf16-matmul / whole-epoch kernel, uint8 streaming"
SUP2_F32_LABEL = "f32 / whole-epoch kernel / superstep 2"
SUP4_F32_LABEL = "f32 / whole-epoch kernel / superstep 4"
SUP_F32_LABEL = "f32 / whole-epoch kernel / superstep 8"
SUP_BF16_LABEL = "bf16-matmul / whole-epoch kernel / superstep 8"

# (label, dtype, superstep); the first entry is the baseline. K=2/4 rows
# joined after the r05 window left K=8 wedge-suspect: most of the grid
# amortization accrues by small K, and a safe small-K win must be
# promotable without waiting for K=8 to be cleared.
CANDIDATES = (
    (F32_LABEL, "float32", 1),
    (BF16_LABEL, "bfloat16", 1),
    (SUP2_F32_LABEL, "float32", 2),
    (SUP4_F32_LABEL, "float32", 4),
    (SUP_F32_LABEL, "float32", 8),
    (SUP_BF16_LABEL, "bfloat16", 8),
)


def pick_best(rows):
    """Stage 1, matrix-only and free: the fastest MEASURED candidate.

    Returns ((label, dtype, superstep, value, baseline_value), reason) or
    (None, reason) when nothing beats the baseline (or the baseline itself
    is missing/unmeasured — promotion is only meaningful against it)."""
    by_label = {r["label"]: r for r in rows}
    base = by_label.get(F32_LABEL)
    if base is None or base["value"] is None:
        return None, "matrix is missing a measured f32/superstep-1 baseline"
    best_label, best_d, best_k = CANDIDATES[0][:3]
    best_v = base["value"]
    unmeasured = []
    for label, d, k in CANDIDATES[1:]:
        r = by_label.get(label)
        if r is None or r["value"] is None:
            unmeasured.append(label)
            continue
        if r["value"] > best_v:
            best_label, best_d, best_k, best_v = label, d, k, r["value"]
    # distinguish a full-sweep verdict from an incomplete matrix on EVERY
    # outcome — a flaky window must never read as a performance verdict,
    # whether the baseline "won" by default or a candidate won over rows
    # that never measured (the committed evidence records the gap too)
    missing = (f"; NOTE {len(unmeasured)} candidate row(s) unmeasured: "
               f"{unmeasured}" if unmeasured else "")
    if best_label == F32_LABEL:
        return None, (f"baseline f32/superstep-1 is already fastest among "
                      f"the measured rows ({best_v:,.0f} img/s/chip)"
                      f"{missing}")
    return ((best_label, best_d, best_k, best_v, base["value"], unmeasured),
            (f"{best_label!r} wins {best_v:,.0f} vs baseline "
             f"{base['value']:,.0f} img/s/chip{missing}"))


def decide(rows, acc_tol: float, measure_acc):
    """The full gate: (calibration_dict_or_None, reason).

    `measure_acc(dtype, superstep) -> float` is called ONLY when the best
    candidate uses bf16 (superstep alone is bitwise-equal by construction),
    so a losing bf16 costs zero extra hardware-window time. Separated from
    I/O so CI can pin every branch with a fake measure_acc."""
    best, reason = pick_best(rows)
    if best is None:
        return None, reason
    label, d, k, v, base_v, unmeasured = best
    evidence = {"winner": label, "value": v, "baseline_value": base_v}
    if unmeasured:
        evidence["unmeasured_candidates"] = unmeasured
    if d == "bfloat16":
        acc_f32 = measure_acc("float32", 1)
        acc_b = measure_acc("bfloat16", k)
        if abs(acc_f32 - acc_b) > acc_tol:
            return None, (f"accuracy parity failed: f32 {acc_f32:.4f} vs "
                          f"bf16 {acc_b:.4f} (tol {acc_tol})")
        evidence.update(acc_f32=round(acc_f32, 4), acc_bf16=round(acc_b, 4))
        reason += f" with accuracy parity ({acc_f32:.4f}/{acc_b:.4f})"
    else:
        reason += " (superstep only: bitwise-equal math, no accuracy gate)"
    return ({"epoch_kernel_dtype": d, "epoch_kernel_superstep": k,
             "evidence": evidence}, reason)


def measure_accuracy(dtype: str, superstep: int, epochs: int) -> float:
    """Final test accuracy of an `epochs`-epoch single-chip epoch-kernel
    training run — bench.py's ONE accuracy helper (measure_train_accuracy),
    so this gate and `bench.py --mode accuracy` can never silently measure
    different workloads. The key impl is rbg: that is the engine of the
    flagless configuration this gate promotes (and both sides of the
    comparison share it — the gate isolates DTYPE effects)."""
    from bench import measure_train_accuracy
    acc, _ = measure_train_accuracy("pallas_epoch", dtype, superstep,
                                    "rbg", epochs)
    return acc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--matrix", required=True,
                   help="variant-matrix artifact (bench_matrix.py --out)")
    p.add_argument("--epochs", type=int, default=10,
                   help="epochs per accuracy-parity run")
    p.add_argument("--acc_tol", type=float, default=0.01)
    p.add_argument("--out", default=str(
        pathlib.Path(__file__).resolve().parent.parent
        / "bench_calibration.json"))
    a = p.parse_args(argv)

    # The launcher's JAX_PLATFORMS intent must win over any pre-registered
    # accelerator plugin BEFORE the first backend query below — env-var-only
    # selection can leave a dead tunnel's plugin hanging the on_tpu_backend
    # probe (the same dance as the trainer CLI).
    from pytorch_ddp_mnist_tpu.parallel.wireup import _honor_platform_env
    _honor_platform_env()

    with open(a.matrix) as f:
        artifact = json.load(f)

    # Stage 1 (free): anything to promote at all?
    best, reason = pick_best(artifact["variants"])
    if best is None:
        print(f"promote_epoch_dtype: {reason}", file=sys.stderr)
        return 1
    if best[1] == "bfloat16":
        # accuracy runs need the real chip
        from pytorch_ddp_mnist_tpu.parallel.wireup import on_tpu_backend
        if not on_tpu_backend():
            print("promote_epoch_dtype: bf16 candidate needs the accuracy "
                  "gate on a real TPU backend", file=sys.stderr)
            return 1

    cal, reason = decide(
        artifact["variants"], a.acc_tol,
        lambda d, k: measure_accuracy(d, k, a.epochs))
    print(f"promote_epoch_dtype: {reason}", file=sys.stderr)
    if cal is None:
        return 1
    cal["evidence"].update(matrix=a.matrix,
                           matrix_timestamp=artifact.get("timestamp"),
                           epochs=a.epochs, reason=reason)
    with open(a.out, "w") as f:
        json.dump(cal, f, indent=1)
        f.write("\n")
    print(f"promote_epoch_dtype: wrote {a.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    # rc contract (ADVICE r4): 0 = promoted, 1 = the RESERVED "not
    # promoted" verdict, 2 = the gate itself crashed (missing/corrupt
    # matrix, traceback) — so callers can tell a losing candidate from a
    # broken gate. A bare uncaught exception would exit 1 and masquerade
    # as "not promoted".
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception:
        import traceback
        traceback.print_exc()
        sys.exit(2)
