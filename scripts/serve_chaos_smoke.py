#!/usr/bin/env python
"""Serve chaos smoke: crash, wedge, and torn-checkpoint-swap a replica
fleet under open-loop load, and prove nothing admitted was ever lost.

    python scripts/serve_chaos_smoke.py [--requests N] [--offered_rps R]

The serving front door of docs/ROBUSTNESS.md (`make serve-chaos-smoke`).
Three legs, all on CPU (the fleet behaves identically on any backend),
all under a `--telemetry`-style trace whose fleet/reload records are
schema-validated at the end:

  1. KILL-MID-BURST — a 2-replica fleet serves a spike-shaped open-loop
     burst (serve/loadgen.py `--shape spike`) while an injected
     `engine_crash` kills one replica's engine mid-burst. The survivor
     absorbs the failover; the verdict requires measured availability
     1.0 (every admitted request answered), >= 1 crash quarantine, > 0
     retried requests, and bitwise-identical predictions to a direct
     single-engine pass over the same rows.
  2. WEDGE-THEN-WATCHDOG — an injected `engine_wedge` hangs a dispatched
     batch (the handle ages, never errors). The fleet's supervisor must
     notice via `oldest_inflight_age`, quarantine the replica, fail the
     wedged futures over to the survivor, and restart the wedged
     replica. Same verdict: availability 1.0, >= 1 wedge, > 0 retried,
     bitwise parity.
  3. TORN-CHECKPOINT-SWAP — a `ReloadWatcher` polls a live checkpoint
     directory while background traffic flows: a good commit hot-swaps
     every replica behind a drain (each swap's `outstanding_at_swap`
     must be 0 — validated from the trace by check_telemetry); an
     injected `reload_torn` validation fault and an intact-but-NaN
     checkpoint are REFUSED BY NAME with the incumbent still serving; an
     actually-truncated newest payload makes the shared walk fall back
     to the newest intact step instead (newest-promotable wins — a torn
     commit costs only the step it tore); a final good commit promotes.
     Verdict: 3 reloads (one of them the torn-fallback), 2 named
     refusals, serving_step at the last good commit, zero failed
     requests throughout.

Then `scripts/check_telemetry.py --require serve.fleet.,serve.reload.`
gates the whole trace: schema-valid records, the fleet/reload event
contract (known event names, outstanding_at_swap == 0, non-empty
refusal reasons), and the serve.fleet.* / serve.reload.* registry
metrics present in the final snapshot.

Exit codes: 0 = all legs held; 1 = any leg or the telemetry gate
failed; 75 = skipped, no usable jax runtime (same convention as
chaos_smoke.py).
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_REPLICAS = 2
MAX_BATCH = 16
WEDGE_TIMEOUT_S = 0.15


# the per-leg counters: the smoke shares ONE process registry across
# legs (so the final snapshot carries serve.fleet.* for --require), which
# makes every counter cumulative — each leg reads its own contribution
# as an after-minus-before delta
_LEG_COUNTERS = ("serve.completed", "serve.failed", "serve.rejected",
                 "serve.fleet.crashes", "serve.fleet.wedges",
                 "serve.fleet.retried_requests", "serve.fleet.restarts")


def _counter_values(reg) -> dict:
    snap = reg.snapshot()["counters"]
    return {k: snap.get(k, 0) for k in _LEG_COUNTERS}


def _direct_predictions(params, rows):
    """The parity target: every row through one untouched engine."""
    import numpy as np
    from pytorch_ddp_mnist_tpu.serve import InferenceEngine
    eng = InferenceEngine(params, max_batch=MAX_BATCH)
    preds = [int(eng.predict(np.stack([r]))[0]) for r in rows]
    eng.close()
    return preds


def _fleet(params, registry, **kw):
    from pytorch_ddp_mnist_tpu.serve import FleetService, InferenceEngine
    return FleetService(
        lambda p: InferenceEngine(p, max_batch=MAX_BATCH), params,
        n_replicas=N_REPLICAS, max_batch=MAX_BATCH, max_delay_ms=1.0,
        registry=registry, wedge_timeout_s=WEDGE_TIMEOUT_S,
        retry_budget=3, **kw)


def _load_leg(params, registry, expect, *, fault: str, shape: str,
              requests: int, offered_rps: float, expect_direct) -> dict:
    """Legs 1 and 2 share this harness: inject `fault`, drive the
    open-loop generator through a fresh fleet, compare predictions
    bitwise against the direct pass, and require zero broken promises
    plus the leg's expected failure counters."""
    from pytorch_ddp_mnist_tpu.serve import run_until_drained
    from pytorch_ddp_mnist_tpu.serve.loadgen import (request_rows,
                                                     run_open_loop)
    from pytorch_ddp_mnist_tpu.utils import faultpoints

    before = _counter_values(registry)
    faultpoints.install(fault)
    try:
        rows = request_rows(requests, "float32", seed=1)
        fleet = _fleet(params, registry)
        out = run_until_drained(
            fleet, run_open_loop(fleet, offered_rps=offered_rps,
                                 n_requests=requests, seed=0, rows=rows,
                                 shape=shape))
    finally:
        faultpoints.install("")   # disarm before the next leg
    d = {k: v - before[k]
         for k, v in _counter_values(registry).items()}

    completed, failed = d["serve.completed"], d["serve.failed"]
    avail = (completed / (completed + failed)
             if completed + failed else 0.0)
    served = [p for p in out["predictions"] if p is not None]
    # rejects leave None predictions and are honest backpressure; every
    # SERVED prediction must match the direct engine bitwise
    mismatches = sum(1 for p, e in zip(out["predictions"], expect_direct)
                     if p is not None and p != e)
    verdict = {
        "fault": fault, "shape": shape,
        "requests": requests, "served": len(served),
        "rejected": d["serve.rejected"], "failed": failed,
        "availability": round(avail, 6),
        "crashes": d["serve.fleet.crashes"],
        "wedges": d["serve.fleet.wedges"],
        "retried_requests": d["serve.fleet.retried_requests"],
        "restarts": d["serve.fleet.restarts"],
        "bitwise_mismatches": mismatches,
    }
    problems = []
    if failed:
        problems.append(f"{failed} admitted requests failed")
    if avail < 1.0:
        problems.append(f"availability {avail:.6f} < 1.0")
    if mismatches:
        problems.append(f"{mismatches} served predictions diverged from "
                        f"the direct engine")
    for counter, floor in expect.items():
        got = d[f"serve.fleet.{counter}"]
        if got < floor:
            problems.append(f"{counter}={got} < expected >= {floor}")
    if d["serve.fleet.retried_requests"] < 1:
        problems.append("no request was ever failed over (the fault "
                        "never bit, or the failover path is dead)")
    verdict["problems"] = problems
    return verdict


def _truncate(path: str, n: int) -> None:
    with open(path, "r+b") as f:
        f.truncate(n)


async def _reload_leg(params, params_new, registry, ckpt_dir) -> dict:
    """Leg 3: hot reload under traffic — good swap, injected-torn /
    actually-torn / NaN refusals by name, then a final good swap."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from pytorch_ddp_mnist_tpu.serve.loadgen import request_rows
    from pytorch_ddp_mnist_tpu.serve.reload import ReloadWatcher
    from pytorch_ddp_mnist_tpu.train.ckpt_manager import CheckpointManager
    from pytorch_ddp_mnist_tpu.utils import faultpoints

    mgr = CheckpointManager(ckpt_dir)
    key = np.zeros(2, np.uint32)
    fleet = _fleet(params, registry, serving_step=0)
    watcher = ReloadWatcher(fleet, ckpt_dir)
    rows = request_rows(64, "float32", seed=2)

    served = {"n": 0, "errors": 0}
    stop = asyncio.Event()

    async def traffic():
        i = 0
        while not stop.is_set():
            try:
                await fleet.handle(rows[i % len(rows)])
                served["n"] += 1
            except Exception:   # noqa: BLE001 — the verdict counts these
                served["errors"] += 1
            i += 1
            await asyncio.sleep(0.002)

    problems = []
    t = asyncio.get_running_loop().create_task(traffic())
    try:
        # 1. good commit -> hot swap behind per-replica drains
        mgr.save(params_new, key, "threefry2x32", step=1, epoch=0, offset=0)
        if await watcher.poll_once() != "reloaded":
            problems.append("good step 1 did not reload")

        # 2. injected validation fault (the reload_torn fault point):
        # refused by name, incumbent untouched
        mgr.save(params_new, key, "threefry2x32", step=2, epoch=0, offset=0)
        faultpoints.install("reload_torn:times=1")
        try:
            if await watcher.poll_once() != "refused":
                problems.append("injected reload_torn was not refused")
        finally:
            faultpoints.install("")
        # ...but step 2's file is intact: it must stay refused BY STEP
        # (never re-validated), not get promoted on the next poll
        if await watcher.poll_once() != "idle":
            problems.append("refused step 2 was reconsidered")

        # 3. actually-torn payload: truncate step 3's committed blob.
        # The newer commit reopens the question and the shared walk falls
        # back PAST the torn newest to the newest intact-and-finite step
        # — step 2, whose earlier refusal was the transient injected
        # fault. Newest-promotable wins (see serve/reload.py docstring):
        # a torn commit costs the fleet nothing but the step it tore.
        mgr.save(params_new, key, "threefry2x32", step=3, epoch=0, offset=0)
        payload = glob.glob(os.path.join(ckpt_dir, "*3*.msgpack"))[0]
        # off-loop: the traffic task shares this event loop, and blocking
        # file IO here would stall the very requests the leg is measuring
        await asyncio.get_running_loop().run_in_executor(
            None, _truncate, payload, 16)
        if await watcher.poll_once() != "reloaded":
            problems.append("torn step 3 did not fall back to the intact "
                            "step 2")
        if fleet.serving_step != 2:
            problems.append(f"torn-fallback serving_step "
                            f"{fleet.serving_step} != 2")

        # 4. intact but non-finite (a diverged run's checkpoint): with
        # only the torn 3 and the NaN 4 beyond serving, NOTHING is
        # promotable — refused by name, incumbent untouched
        p_nan = jax.tree_util.tree_map(lambda a_: jnp.full_like(a_, jnp.nan),
                                       params_new)
        mgr.save(p_nan, key, "threefry2x32", step=4, epoch=0, offset=0)
        if await watcher.poll_once() != "refused":
            problems.append("NaN step 4 was not refused")
        if fleet.serving_step != 2:
            problems.append(f"refusals moved serving_step to "
                            f"{fleet.serving_step} (expected 2)")

        # 5. final good commit promotes past the wreckage
        mgr.save(params_new, key, "threefry2x32", step=5, epoch=0, offset=0)
        if await watcher.poll_once() != "reloaded":
            problems.append("good step 5 did not reload")
        if fleet.serving_step != 5:
            problems.append(f"serving_step {fleet.serving_step} != 5")
    finally:
        stop.set()
        await t
        await watcher.stop()
        snap = fleet.fleet_snapshot()
        await fleet.shutdown()

    if served["errors"]:
        problems.append(f"{served['errors']} requests failed during the "
                        f"reload cycle")
    if served["n"] < 10:
        problems.append(f"only {served['n']} requests flowed — the leg "
                        f"never actually ran under traffic")
    if watcher.reloads != 3:
        problems.append(f"reloads={watcher.reloads} != 3")
    if watcher.refused != 2:
        problems.append(f"refused={watcher.refused} != 2")
    return {
        "served_during_reloads": served["n"],
        "failed": served["errors"],
        "availability": (round(served["n"]
                               / (served["n"] + served["errors"]), 6)
                         if served["n"] + served["errors"] else 0.0),
        "reloads": watcher.reloads, "refused": watcher.refused,
        "serving_step": fleet.serving_step,
        "generation": snap["generation"],
        "problems": problems,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replica-fleet crash/wedge/hot-reload chaos smoke")
    ap.add_argument("--requests", type=int, default=200,
                    help="open-loop requests per load leg")
    ap.add_argument("--offered_rps", type=float, default=800.0)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    ap.add_argument("--keep_workdir", action="store_true")
    a = ap.parse_args(argv)

    try:
        import jax  # noqa: F401
    except Exception as e:  # noqa: BLE001 — no runtime = skip, not fail
        print(f"serve_chaos_smoke: SKIP — no usable jax runtime ({e})",
              file=sys.stderr)
        return 75

    import jax
    from pytorch_ddp_mnist_tpu import telemetry
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.serve.loadgen import request_rows
    from pytorch_ddp_mnist_tpu.telemetry import flight

    work = a.workdir or tempfile.mkdtemp(prefix="pdmt_serve_chaos_")
    os.makedirs(work, exist_ok=True)
    tel_dir = os.path.join(work, "telemetry")
    ckpt_dir = os.path.join(work, "ckpts")
    os.makedirs(ckpt_dir, exist_ok=True)

    telemetry.enable(tel_dir)
    flight.set_dump_dir(tel_dir)
    reg = telemetry.get_registry()

    params = init_mlp(jax.random.key(0))
    params_new = init_mlp(jax.random.key(1))
    t0 = time.monotonic()
    # the parity target once: legs 1 and 2 drive the same seeded rows
    direct = _direct_predictions(params,
                                 request_rows(a.requests, "float32", seed=1))

    legs = {}
    # leg 1: replica 0's engine crashes mid-burst (after its 2nd batch)
    legs["kill_mid_burst"] = _load_leg(
        params, reg, {"crashes": 1},
        fault="engine_crash:after=2:replica=0", shape="spike",
        requests=a.requests, offered_rps=a.offered_rps,
        expect_direct=direct)
    # leg 2: replica 1 wedges (a dispatched batch hangs for 1s; the
    # watchdog must fail it over within WEDGE_TIMEOUT_S)
    legs["wedge_then_watchdog"] = _load_leg(
        params, reg, {"wedges": 1},
        fault="engine_wedge:delay_s=1.0:replica=1", shape="poisson",
        requests=a.requests, offered_rps=a.offered_rps,
        expect_direct=direct)
    # leg 3: hot reload under traffic, with torn/NaN refusals by name
    legs["torn_checkpoint_swap"] = asyncio.run(
        _reload_leg(params, params_new, reg, ckpt_dir))

    # stamp the final registry snapshot into the trace (what --require
    # gates on), flush the flight ring, close the JSONL
    telemetry.get_tracer().snapshot(reg)
    flight.dump(reason="serve chaos smoke")
    telemetry.disable()

    check = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_telemetry.py"),
         "--require", "serve.fleet.,serve.reload.", tel_dir],
        capture_output=True, text=True)
    telemetry_ok = check.returncode == 0
    if not telemetry_ok:
        print(f"serve_chaos_smoke: telemetry gate failed:\n{check.stdout}"
              f"\n{check.stderr}", file=sys.stderr)

    problems = [f"{leg}: {p}" for leg, v in legs.items()
                for p in v.pop("problems")]
    verdict = {
        "serve_chaos_smoke": "ok" if not problems and telemetry_ok
        else "fail",
        "replicas": N_REPLICAS,
        "wedge_timeout_s": WEDGE_TIMEOUT_S,
        # the headline: the worst measured availability across legs —
        # the number the fleet exists to hold at 1.0 under faults
        "availability": min(v["availability"] for v in legs.values()),
        "legs": legs,
        "telemetry": "validated" if telemetry_ok else "FAILED",
        "dur_s": round(time.monotonic() - t0, 2),
    }
    if problems:
        verdict["problems"] = problems
        for p in problems:
            print(f"serve_chaos_smoke: FAIL — {p}", file=sys.stderr)
    print(json.dumps(verdict))
    if not a.keep_workdir and a.workdir is None and not problems \
            and telemetry_ok:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if not problems and telemetry_ok else 1


if __name__ == "__main__":
    sys.exit(main())
