#!/bin/bash
# Unattended hardware-window runner: poll (forever, or $PDMT_WINDOW_POLL_MAX
# probes) for the TPU backend from fresh hang-bounded subprocesses, then run
# the full measurement queue (scripts/measure_hw.sh) and commit the results.
# If the window closes mid-queue (the r05 morning pass lost its tunnel after
# 10 of 12 matrix rows), the runner goes BACK to polling and reruns the queue
# on the next window — up to $PDMT_WINDOW_MAX_PASSES passes or until one pass
# completes with every phase green.
#
# This is the in-repo version of the /tmp watcher used in rounds 3-4 so the
# pattern survives the machine: start it with nohup at the beginning of a
# session whose tunnel is down, and the measurement queue fires the moment a
# window opens — the single most time-critical action on a backend whose
# outages run 8-10+ hours and whose windows can be minutes
# (docs/PERF.md outage log).
#
# Usage: nohup scripts/hw_window.sh [matrix_out.json] >> /tmp/hw_window.log 2>&1 &
#   PDMT_WINDOW_POLL_MAX     max probes per pass before giving up (default:
#                            unlimited)
#   PDMT_WINDOW_MAX_PASSES   max measurement passes (default 3)
#   PDMT_MEASURE_CMD         the per-pass measurement script (default
#                            scripts/measure_hw.sh; tests inject a stub to
#                            pin the multi-pass/commit mechanics)
set -u
cd "$(dirname "$0")/.."
OUT="${1:-bench_matrix_hw.json}"
MAX="${PDMT_WINDOW_POLL_MAX:-0}"
PASSES="${PDMT_WINDOW_MAX_PASSES:-3}"
MEASURE="${PDMT_MEASURE_CMD:-scripts/measure_hw.sh}"

echo "=== hw_window start $(date -u +%H:%M:%SZ) (out=$OUT, passes<=$PASSES) ==="
rc=1
for ((pass = 1; pass <= PASSES; pass++)); do
  n=0
  while true; do
    if timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "backend UP at $(date -u +%H:%M:%SZ) (pass $pass)"; break
    fi
    n=$((n + 1))
    if ((MAX > 0 && n >= MAX)); then
      echo "backend still down after $n probes; giving up"; exit 1
    fi
    echo "backend still down $(date -u +%H:%M:%SZ)"; sleep 90
  done

  # Pass 1 writes $OUT; later passes get _p2/_p3 suffixes so a partial
  # earlier artifact is never overwritten by a worse retry.
  if ((pass == 1)); then PASS_OUT="$OUT"; else
    PASS_OUT="${OUT%.json}_p${pass}.json"; fi
  SWEEP="${PASS_OUT%.json}_sweep.log"
  echo "hardware window opened $(date -u +%H:%M:%SZ) — measurement pass $pass" > "$SWEEP"
  PDMT_WINDOW_WAIT=300 bash "$MEASURE" "$PASS_OUT" >> "$SWEEP" 2>&1
  rc=$?
  echo "measure_hw rc=$rc" >> "$SWEEP"
  # One pathspec per git-add: a single multi-file add aborts WHOLE on any
  # missing path (e.g. bench_calibration.json when the gate didn't promote),
  # which silently committed nothing in the r05 morning pass.
  for f in "$PASS_OUT" "${PASS_OUT%.json}_full.json" \
           bench_calibration.json "$SWEEP"; do
    git add -- "$f" 2>/dev/null || echo "hw_window: no $f to commit"
  done
  git commit -q -m "Hardware window: automated measurement pass $pass ($PASS_OUT)" || true
  if ((rc == 0)); then
    echo "=== hw_window done rc=0 after pass $pass $(date -u +%H:%M:%SZ) ==="
    exit 0
  fi
  echo "pass $pass incomplete (rc=$rc); re-polling for the next window"
done
echo "=== hw_window done rc=$rc after $PASSES passes $(date -u +%H:%M:%SZ) ==="
exit $rc
