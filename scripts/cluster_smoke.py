#!/usr/bin/env python
"""Cluster-forensics smoke: journal a small world, wedge a rank in a
collective, and prove the forensics name the culprit.

    python scripts/cluster_smoke.py [--world 2] [--workdir DIR] ...

The front door of docs/OBSERVABILITY.md §Cluster forensics
(`make cluster-smoke`). Three legs:

  1. CLEAN   — a `--parallel --journal --telemetry` world trains one
     epoch; every rank's collective journal must agree (no desync), the
     `cluster.*` AND `ddp.*` metric families must gate in ONE
     check_telemetry invocation (`--require cluster.,ddp.` — the
     comma-prefix form), and the Perfetto export must carry the per-rank
     collective tracks (with cross-rank seq flow arrows at world >= 2).
  2. HANG    — the same world with `PDMT_FAULT=collective_timeout:rank=0`:
     rank 0's startup barrier raises the DEADLINE_EXCEEDED-shaped error a
     dead collective produces, its journal keeps the barrier's OPEN enter
     record, and `trace report --cluster` must render a hang report
     naming the stuck seq, its kind, and every rank's last journal
     position — instead of the silent wedge the fault used to be.
  3. DESYNC  — a synthetic journal pair recording DIFFERENT collectives
     at the same seq: `trace report --cluster` must exit 3 naming both
     ranks and the diverging collective. Process-free, so this leg runs
     even in the world-1 fallback.

Exit codes: 0 = every leg held; 1 = any leg failed; 75 = skipped, this
jax has no CPU multiprocess collectives (rerun with --world 1 — the
chaos_smoke convention, which `make cluster-smoke` does automatically).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(rank: int, port: int, argv, world: int, extra_env=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(world),
        "RANK": str(rank),
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train", *argv],
        cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _run_world(argv, world: int, timeout: float, extra_env=None):
    port = _free_port()
    procs = [_spawn(r, port, argv, world, extra_env) for r in range(world)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, err = p.communicate()
            outs.append((None, out, err))
    for p in procs:
        if p.poll() is None:
            p.kill()
        p.wait()
    return outs


def _run_hang_world(argv, world: int, timeout: float, fault: str):
    """Run a world expecting rank 0 to die at the faulted barrier; the
    survivors (blocked in the barrier whose peer will never arrive) are
    reaped once it does — the gang-scheduler model chaos_smoke uses.
    Returns rank 0's (rc, out, err)."""
    port = _free_port()
    procs = [_spawn(r, port, argv, world,
                    {"PDMT_FAULT": fault} if r == 0 else None)
             for r in range(world)]
    victim = procs[0]
    deadline = time.monotonic() + timeout
    while victim.poll() is None and time.monotonic() < deadline:
        time.sleep(0.25)
    for p in procs[1:]:
        if p.poll() is None:
            p.kill()
    rc = victim.poll()
    out, err = victim.communicate()
    for p in procs[1:]:
        p.communicate()
    return rc, out, err


def _tool(args, timeout=120.0):
    return subprocess.run(
        [sys.executable, *args], cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PYTHONPATH=REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")))


def _forge_desync_pair(out_dir: str) -> None:
    """Two journals recording DIFFERENT collectives at the same seq —
    the synthetic desync the acceptance pins."""
    os.makedirs(out_dir, exist_ok=True)
    now = time.time()
    for rank, (kind, nbytes) in enumerate(
            (("allreduce", 1024), ("reduce_scatter", 512))):
        name = "journal.jsonl" if rank == 0 else f"journal.rank{rank}.jsonl"
        with open(os.path.join(out_dir, name), "w") as f:
            f.write(json.dumps({"kind": "journal_start", "v": 1,
                                "rank": rank, "world": 2,
                                "t_wall": now, "t_mono": 0.0}) + "\n")
            f.write(json.dumps({"kind": "coll", "seq": 0, "k": kind,
                                "axis": "dp", "bytes": nbytes, "bucket": 0,
                                "step": 0, "t_enter": 0.0, "t_exit": 0.1,
                                "t_wall": now}) + "\n")
            f.write(json.dumps({"kind": "journal_end", "seq": 1,
                                "t_wall": now, "t_mono": 0.2}) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="collective-journal forensics smoke (clean / hang / "
                    "desync legs)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=420.0)
    ap.add_argument("--keep_workdir", action="store_true")
    a = ap.parse_args(argv)

    # CPU multiprocess collectives need jax >= 0.5 (the chaos_smoke /
    # test_multiprocess gate): absent capability = skip signal 75, and
    # the Makefile reruns at --world 1.
    import jax
    if (a.world > 1
            and tuple(int(x)
                      for x in jax.__version__.split(".")[:2]) < (0, 5)):
        print("cluster_smoke: SKIP — this jaxlib has no CPU multiprocess "
              "collectives (needs jax >= 0.5)", file=sys.stderr)
        return 75

    work = a.workdir or tempfile.mkdtemp(prefix="pdmt_cluster_")
    os.makedirs(work, exist_ok=True)
    clean_dir = os.path.join(work, "clean")
    hang_dir = os.path.join(work, "hang")
    desync_dir = os.path.join(work, "desync")
    base = ["--parallel", "--wireup_method", "env", "--kernel", "xla",
            "--n_epochs", "1", "--limit", "256", "--batch_size", "64",
            "--lr", "0.1", "--checkpoint", "",
            "--path", os.path.join(work, "data")]

    def fail(msg, *streams):
        print(f"cluster_smoke: FAIL — {msg}", file=sys.stderr)
        for s in streams:
            print(s, file=sys.stderr)
        return 1

    # -- 1. CLEAN: journaled world, metric gate, report, export ----------
    outs = _run_world(base + ["--journal", "--telemetry", clean_dir],
                      a.world, a.timeout)
    if any(rc != 0 for rc, _, _ in outs):
        return fail("clean journaled world",
                    *[f"rank {r} rc={rc}\n{o}\n{e}"
                      for r, (rc, o, e) in enumerate(outs)])
    # the comma-prefix form: TWO metric families, ONE checker invocation
    chk = _tool([os.path.join(REPO, "scripts", "check_telemetry.py"),
                 "--require", "cluster.,ddp.", clean_dir])
    if chk.returncode != 0:
        return fail("check_telemetry --require cluster.,ddp.",
                    chk.stdout, chk.stderr)
    rep = _tool(["-m", "pytorch_ddp_mnist_tpu", "trace", "report",
                 "--cluster", "--json", clean_dir])
    if rep.returncode != 0:
        return fail("trace report --cluster (clean)", rep.stdout,
                    rep.stderr)
    report = json.loads(rep.stdout)
    if (not report["desync"]["ok"] or report["n_ranks"] != a.world
            or report["totals"]["collectives"] == 0
            or report["hang"]["stuck"] is not None):
        return fail(f"clean report wrong: {json.dumps(report)[:800]}")
    exp = _tool(["-m", "pytorch_ddp_mnist_tpu", "trace", "export",
                 clean_dir, "-o",
                 os.path.join(clean_dir, "trace.chrome.json")])
    if exp.returncode != 0:
        return fail("trace export (clean)", exp.stdout, exp.stderr)
    with open(os.path.join(clean_dir, "trace.chrome.json")) as f:
        chrome = json.load(f)
    colls = [e for e in chrome["traceEvents"]
             if e.get("cat") == "collective"]
    arrows = [e for e in chrome["traceEvents"]
              if e.get("cat") == "collective_flow"]
    if not colls:
        return fail("chrome trace has no collective track events")
    if a.world >= 2 and not any(e.get("ph") == "s" for e in arrows):
        return fail("chrome trace has no cross-rank collective flow "
                    "arrows at world >= 2")

    # -- 2. HANG: injected collective_timeout names the stuck seq --------
    rc, out, err = _run_hang_world(
        base + ["--journal", "--telemetry", hang_dir], a.world,
        a.timeout, "collective_timeout:rank=0")
    if rc in (0, None) or "[cluster] collective timeout" not in err:
        return fail(f"hang leg: rank 0 rc={rc}, expected the named "
                    f"collective-timeout exit", out, err)
    rep = _tool(["-m", "pytorch_ddp_mnist_tpu", "trace", "report",
                 "--cluster", "--json", hang_dir])
    if rep.returncode != 0:
        return fail("trace report --cluster (hang)", rep.stdout,
                    rep.stderr)
    report = json.loads(rep.stdout)
    stuck = report["hang"]["stuck"]
    if stuck is None or stuck["kind"] != "barrier" or stuck["rank"] != 0:
        return fail(f"hang report did not name the stuck barrier: "
                    f"{json.dumps(report['hang'])[:800]}")
    who = report["hang"]["who_is_where"]
    if len(who) != a.world or not all("seq" in w for w in who):
        return fail(f"who-is-where table incomplete: {who}")
    human = _tool(["-m", "pytorch_ddp_mnist_tpu", "trace", "report",
                   "--cluster", hang_dir])
    if f"HANG: rank 0 entered collective seq {stuck['seq']}" \
            not in human.stdout:
        return fail("human hang report does not name the stuck seq",
                    human.stdout)
    # the flight dump beside the journals carries the fault + hang trail,
    # rank-stamped (the checker validates the v2 rank contract)
    chk = _tool([os.path.join(REPO, "scripts", "check_telemetry.py"),
                 hang_dir])
    if chk.returncode != 0:
        return fail("check_telemetry on the hang dir", chk.stdout,
                    chk.stderr)
    if not report["faults"]:
        return fail("hang report carries no flight fault context")

    # -- 3. DESYNC: synthetic pair exits 3 naming both ranks -------------
    _forge_desync_pair(desync_dir)
    rep = _tool(["-m", "pytorch_ddp_mnist_tpu", "trace", "report",
                 "--cluster", desync_dir])
    if rep.returncode != 3:
        return fail(f"desync leg: expected exit 3, got {rep.returncode}",
                    rep.stdout, rep.stderr)
    if "rank 0" not in rep.stderr or "rank 1" not in rep.stderr:
        return fail("desync verdict does not name both ranks", rep.stderr)

    print(json.dumps({
        "cluster_smoke": "ok", "world": a.world,
        "hang_seq": stuck["seq"], "hang_kind": stuck["kind"],
        "desync_exit": 3,
        "collective_track_events": len(colls),
        "flow_arrows": sum(1 for e in arrows if e.get("ph") == "s"),
    }))
    if not a.keep_workdir and a.workdir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
