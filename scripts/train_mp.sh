#!/bin/bash
# Multi-process CPU stand-in (bash twin of train_mp.csh, since csh may not be
# installed). 4 localhost processes rendezvous via the env:// wireup branch —
# the analog of the reference's `mpiexec -n 4 … --wireup_method mpich` run
# (/root/reference/train_cpu_mp.csh:1) with gloo forced on no-GPU hosts
# (mnist_cpu_mp.py:248-250).
set -e
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
export WORLD_SIZE=${WORLD_SIZE:-4}
export MASTER_ADDR=127.0.0.1
export MASTER_PORT=${MASTER_PORT:-29531}
pids=()
for r in $(seq 0 $((WORLD_SIZE - 1))); do
    RANK=$r python -m pytorch_ddp_mnist_tpu.cli.train \
        --parallel --wireup_method env --n_epochs 1 "$@" &
    pids+=($!)
done
for p in "${pids[@]}"; do wait "$p"; done
