#!/bin/csh
# Multi-process CPU stand-in — the reference train_cpu_mp.csh analog
# (/root/reference/train_cpu_mp.csh:1: mpiexec -n 4 ... --parallel
# --wireup_method mpich). Without an MPI launcher in the image, the same
# 4-process rendezvous is driven by env-var wireup (the reference's fallback
# branch, mnist_cpu_mp.py:147-185): each process gets RANK/WORLD_SIZE and
# meets at the coordinator.
cd `dirname $0`/..
setenv JAX_PLATFORMS cpu
setenv WORLD_SIZE 4
setenv MASTER_ADDR 127.0.0.1
setenv MASTER_PORT 29531
foreach r (0 1 2 3)
    env RANK=$r python -m pytorch_ddp_mnist_tpu.cli.train \
        --parallel --wireup_method env --n_epochs 1 $argv:q &
end
wait
