"""Run the full bench variant matrix and print a markdown table + MFU.

Each variant is one `bench.py` invocation (fresh process — fresh compile
cache namespace, no cross-variant state). Usage:

    python scripts/bench_matrix.py            # all variants on the default backend
    python scripts/bench_matrix.py --quick    # fewer fused epochs (CI smoke)

The MFU estimate uses the analytic FLOPs of the train step (see docs/PERF.md:
fwd 118,016 MACs/img; backward adds ~2x for the dgrad+wgrad pairs) against a
v5e bf16 peak of 197 TFLOP/s.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

# (label, extra argv) — every combination that composes semantically.
# Flags are explicit (never `auto`) so the matrix measures the same variant
# on any backend regardless of bench.py's auto-resolution.
VARIANTS = [
    ("f32 / XLA / threefry (reference semantics)",
     ["--kernel", "xla", "--impl", "threefry2x32"]),
    ("f32 / Pallas / threefry",
     ["--kernel", "pallas", "--impl", "threefry2x32"]),
    ("bf16 / XLA / threefry",
     ["--kernel", "xla", "--dtype", "bfloat16", "--impl", "threefry2x32"]),
    ("f32 / XLA / rbg", ["--kernel", "xla", "--impl", "rbg"]),
    ("bf16 / XLA / rbg",
     ["--kernel", "xla", "--dtype", "bfloat16", "--impl", "rbg"]),
    ("f32 / Pallas / rbg (bench default on TPU)",
     ["--kernel", "pallas", "--impl", "rbg"]),
    # TPU-only (core-PRNG dropout inside the kernel); FAILS on CPU hosts by
    # design — measured ~3% below the per-step default (docs/PERF.md).
    ("f32 / Pallas / in-kernel PRNG", ["--kernel", "pallas_rng"]),
    # TPU-only, single-chip: the whole-epoch kernel — the headline variant
    # (weights VMEM-resident across all steps; docs/PERF.md).
    ("f32 / whole-epoch kernel (single-chip headline)",
     ["--kernel", "pallas_epoch"]),
]

MACS_FWD_PER_IMG = 784 * 128 + 128 * 128 + 128 * 10      # 118,016
FLOPS_PER_IMG = 3 * 2 * MACS_FWD_PER_IMG                  # fwd + ~2x bwd
V5E_PEAK_BF16 = 197e12


def run_variant(argv, epochs: int):
    cmd = [sys.executable, "bench.py", "--epochs", str(epochs)] + argv
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        return None, ["timeout after 1200s"]
    if out.returncode != 0:
        return None, (out.stderr or out.stdout).strip().splitlines()[-1:]
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    return (json.loads(line[-1]) if line else None), None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true", help="5 fused epochs")
    p.add_argument("--epochs", type=int, default=None)
    a = p.parse_args(argv)
    epochs = a.epochs if a.epochs is not None else (5 if a.quick else 50)
    if epochs < 1:
        p.error("--epochs must be >= 1")

    rows = []
    for label, extra in VARIANTS:
        rec, err = run_variant(extra, epochs)
        if rec is None:
            print(f"  {label}: FAILED {err}", file=sys.stderr)
            rows.append((label, None))
            continue
        rows.append((label, rec["value"]))
        print(f"  {label}: {rec['value']:,.0f} img/s/chip", file=sys.stderr)

    print("\n| Variant | images/sec/chip | TFLOP/s | MFU (vs 197T bf16 peak) |")
    print("|---|---|---|---|")
    for label, v in rows:
        if v is None:
            print(f"| {label} | (failed) | — | — |")
            continue
        tf = v * FLOPS_PER_IMG / 1e12
        print(f"| {label} | {v:,.0f} | {tf:.2f} | {100 * tf * 1e12 / V5E_PEAK_BF16:.2f}% |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
