"""Run the full bench variant matrix and print a markdown table + MFU.

Each variant is one `bench.py` invocation (fresh process — fresh compile
cache namespace, no cross-variant state). Usage:

    python scripts/bench_matrix.py            # all variants on the default backend
    python scripts/bench_matrix.py --quick    # fewer fused epochs (CI smoke)

The MFU estimate uses the analytic FLOPs of the train step (see docs/PERF.md:
fwd 118,016 MACs/img; backward adds ~2x for the dgrad+wgrad pairs) against a
v5e bf16 peak of 197 TFLOP/s.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys

# `python scripts/bench_matrix.py` puts scripts/ (not the repo root) on
# sys.path; the backend-identity probe imports the package.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# (label, extra argv) — every combination that composes semantically.
# Flags are explicit (never `auto`) so the matrix measures the same variant
# on any backend regardless of bench.py's auto-resolution.
VARIANTS = [
    ("f32 / XLA / threefry (reference semantics)",
     ["--kernel", "xla", "--dtype", "float32", "--impl", "threefry2x32"]),
    ("f32 / Pallas / threefry",
     ["--kernel", "pallas", "--dtype", "float32", "--impl", "threefry2x32"]),
    ("bf16 / XLA / threefry",
     ["--kernel", "xla", "--dtype", "bfloat16", "--impl", "threefry2x32"]),
    ("f32 / XLA / rbg",
     ["--kernel", "xla", "--dtype", "float32", "--impl", "rbg"]),
    ("bf16 / XLA / rbg",
     ["--kernel", "xla", "--dtype", "bfloat16", "--impl", "rbg"]),
    ("f32 / Pallas / rbg (bench default on TPU)",
     ["--kernel", "pallas", "--dtype", "float32", "--impl", "rbg"]),
    # TPU-only (core-PRNG dropout inside the kernel); FAILS on CPU hosts by
    # design — measured ~3% below the per-step default (docs/PERF.md).
    ("f32 / Pallas / in-kernel PRNG",
     ["--kernel", "pallas_rng", "--dtype", "float32"]),
    # TPU-only: the whole-epoch kernel — the headline variant (weights
    # VMEM-resident across all steps, uint8 input streaming; docs/PERF.md).
    # On a 1-chip mesh this is the headline single-chip program; on
    # multi-chip meshes it takes the EXPERIMENTAL in-kernel-ring DDP path
    # and bench.py prints a warning to stderr. --dtype is explicit (like
    # every flag here): bench's `--dtype auto` default reads the committed
    # bf16 calibration for pallas_epoch, which would silently turn the f32
    # rows into bf16 runs — and the promotion gate's f32 baseline with it.
    ("f32 / whole-epoch kernel, uint8 streaming (single-chip headline)",
     ["--kernel", "pallas_epoch", "--dtype", "float32",
      "--superstep", "1"]),
    # In-kernel threefry (VPU cipher): the REFERENCE RNG stream (bitwise
    # models/mlp.py dropout) at epoch-kernel speed — measures the cost of
    # reference RNG semantics vs the core-PRNG row above.
    ("f32 / whole-epoch kernel / in-kernel threefry (reference RNG)",
     ["--kernel", "pallas_epoch", "--dtype", "float32", "--superstep", "1",
      "--impl", "threefry2x32"]),
    # bf16 matmul operands inside the epoch kernel (f32 master weights +
    # accumulation): the f32 epoch kernel is MXU-bound, so this targets the
    # dominant term directly.
    ("bf16-matmul / whole-epoch kernel, uint8 streaming",
     ["--kernel", "pallas_epoch", "--dtype", "bfloat16",
      "--superstep", "1"]),
    # Grid super-stepping: K SGD sub-steps per grid iteration (identical
    # math; amortizes the fixed per-iteration cost). K ascending — most of
    # the amortization accrues by K=2/K=4, and K=8 (which coincided with
    # the r05 outage and is wedge-suspect until cleared) stays last.
    ("f32 / whole-epoch kernel / superstep 2",
     ["--kernel", "pallas_epoch", "--dtype", "float32",
      "--superstep", "2"]),
    ("f32 / whole-epoch kernel / superstep 4",
     ["--kernel", "pallas_epoch", "--dtype", "float32",
      "--superstep", "4"]),
    ("f32 / whole-epoch kernel / superstep 8",
     ["--kernel", "pallas_epoch", "--dtype", "float32",
      "--superstep", "8"]),
    ("bf16-matmul / whole-epoch kernel / superstep 8",
     ["--kernel", "pallas_epoch", "--dtype", "bfloat16", "--superstep", "8"]),
    # The DDP comms axis (round 9): per-strategy gradient communication on
    # the full-device mesh (parallel/collectives.py; bench --mode ddp).
    # On a single chip the three strategies degenerate to the same
    # no-collective program — these rows earn their keep in a MULTI-chip
    # hardware window, where one queue pass measures all three (per-chip
    # rate + scaling efficiency + parity drift land in the artifact line).
    ("DDP comms / pmean baseline (full-mesh, per-step allreduce)",
     ["--mode", "ddp", "--ddp_comm", "pmean"]),
    ("DDP comms / sharded update (reduce-scatter + 1/N SGD + all-gather)",
     ["--mode", "ddp", "--ddp_comm", "sharded"]),
    ("DDP comms / bf16 compressed allreduce",
     ["--mode", "ddp", "--ddp_comm", "bf16"]),
    # Round 12: the int8 error-feedback quantized allreduce and the
    # bucket-pipelined overlap variant, plus the MODEL-SIZE axis (ROADMAP
    # item 2): at param_scale 1 the 118k-param MLP is dispatch-bound and
    # every comm saving is noise — the scale-8 rows (1.9M params, ~7.4 MB
    # f32 gradient) are where the strategies separate and the crossover
    # lives (docs/PERF.md §strategy × model-size crossover).
    ("DDP comms / int8 error-feedback quantized allreduce",
     ["--mode", "ddp", "--ddp_comm", "int8"]),
    ("DDP comms / pmean + bucket-pipelined overlap",
     ["--mode", "ddp", "--ddp_comm", "pmean", "--overlap"]),
    ("DDP comms @ mlp x8 / pmean baseline",
     ["--mode", "ddp", "--ddp_comm", "pmean", "--param_scale", "8"]),
    ("DDP comms @ mlp x8 / sharded update",
     ["--mode", "ddp", "--ddp_comm", "sharded", "--param_scale", "8"]),
    ("DDP comms @ mlp x8 / bf16 compressed",
     ["--mode", "ddp", "--ddp_comm", "bf16", "--param_scale", "8"]),
    ("DDP comms @ mlp x8 / int8 error-feedback quantized",
     ["--mode", "ddp", "--ddp_comm", "int8", "--param_scale", "8"]),
    ("DDP comms @ mlp x8 / int8 + overlap",
     ["--mode", "ddp", "--ddp_comm", "int8", "--overlap",
      "--param_scale", "8"]),
]

# Single source of truth for the roofline math: bench.perf_fields — the
# same formula AND constants as the per-line tflops/mfu fields in
# BENCH_r0X.json, so a FLOP-model change can never skew the two apart.
# (The matrix keeps its historical row key 'mfu_vs_197t_bf16' for
# cross-round diffability.)
from bench import perf_fields  # noqa: E402


def run_variant(argv, epochs: int):
    # --backend_wait must stay well under this function's 1200s row timeout:
    # subprocess.run SIGKILLs on expiry, which would skip bench.py's honest
    # error JSON entirely (its SIGTERM handler never fires on SIGKILL) and
    # burn the whole row budget polling. 300s of polling + the row's own
    # work fits; a longer outage fails the row fast and the retry pass
    # re-measures it.
    cmd = [sys.executable, "bench.py", "--epochs", str(epochs),
           "--backend_wait", "300"] + argv
    try:
        # Unfiltered tracebacks: a failed row's artifact error must carry
        # the real exception, not jax's "internal frames removed" banner
        # (which is all the r05 threefry-row failure recorded).
        # PDMT_STATICS_STAMP=0: every cell would recompute the identical
        # per-process lint+audit stamp; the matrix stamps ONCE at the
        # artifact level instead (main(), the multichip_smoke pattern).
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=1200,
                             env=dict(os.environ,
                                      JAX_TRACEBACK_FILTERING="off",
                                      PDMT_STATICS_STAMP="0"))
    except subprocess.TimeoutExpired:
        return None, ["timeout after 1200s"]
    if out.returncode != 0:
        return None, _failure_lines(out.stderr or out.stdout)
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    return (json.loads(line[-1]) if line else None), None


def _failure_lines(text: str, tail: int = 4, errs: int = 3):
    """Compress a failed row's output into artifact-sized evidence: the
    first `errs` lines naming an exception (ValueError: ..., RuntimeError:
    ...) plus the last `tail` lines — enough to diagnose from the JSON
    without rerunning the row."""
    lines = [ln.rstrip() for ln in text.strip().splitlines() if ln.strip()]
    named = [ln for ln in lines
             if ln.lstrip() == ln and ": " in ln
             and ln.split(":", 1)[0].endswith(("Error", "Exception",
                                               "Interrupt", "Exit"))]
    keep = named[:errs] + [ln for ln in lines[-tail:]
                           if ln not in named[:errs]]
    return keep


def _backend_info() -> dict:
    """Backend identity for the artifact, probed in THIS process (the
    variants run in subprocesses on the same default backend).

    The probe is HANG-BOUNDED: the tunneled TPU backend's outage mode can
    leave a bare jax.devices() blocked forever (no exception to catch —
    parallel/wireup.py's hang-mode notes), which would stall the artifact
    write after an otherwise complete sweep."""
    try:
        from pytorch_ddp_mnist_tpu.parallel.wireup import (
            _honor_platform_env, _probe_devices_bounded, env_seconds)
        _honor_platform_env()
        probe_timeout = env_seconds("PDMT_HANG_TIMEOUT", 30.0)
        status, payload = _probe_devices_bounded(probe_timeout)
        if status != "ok":
            # 'hang' carries a wait_fn closure, not a message — keep the
            # artifact field readable and deterministic (it is diffed
            # across rounds).
            detail = (f"probe did not answer within {probe_timeout:g}s"
                      if status == "hang" else str(payload))
            return {"backend": None, "device_kind": None,
                    "jax_version": None,
                    "backend_probe_error": f"{status}: {detail}"}
        import jax
        dev = payload[0]
        return {"backend": jax.default_backend(),
                "device_kind": getattr(dev, "device_kind", str(dev)),
                "jax_version": jax.__version__}
    except Exception as e:  # matrix still useful without a live backend probe
        return {"backend": None, "device_kind": None,
                "jax_version": None, "backend_probe_error": str(e)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true", help="5 fused epochs")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--retries", type=int, default=1,
                   help="extra passes over variants that failed (e.g. a "
                        "backend outage mid-sweep), re-run at sweep end")
    p.add_argument("--out", default=None,
                   help="write the measured matrix as a JSON artifact "
                        "(per-variant value + timestamp + backend) so perf "
                        "claims are diffable across rounds, e.g. "
                        "bench_matrix_r03.json")
    p.add_argument("--skip", default=None, metavar="SUBSTR",
                   help="skip variants whose label contains SUBSTR (case-"
                        "insensitive); they appear in the artifact as "
                        "explicit null-valued skipped rows. Lets an "
                        "unattended window defer wedge-suspect rows (the "
                        "r05 superstep-8 row ran into a backend outage "
                        "mid-row and could not be cleared of wedging the "
                        "chip) to a final risky phase instead of mid-matrix")
    p.add_argument("--only", default=None, metavar="SUBSTR",
                   help="measure ONLY variants whose label contains SUBSTR "
                        "(case-insensitive); the rest become skipped rows "
                        "(or are reused via --base)")
    p.add_argument("--base", default=None, metavar="ARTIFACT",
                   help="for rows not measured in THIS run (--only/--skip), "
                        "reuse the measured row from this earlier artifact "
                        "instead of recording a skip — marked with a "
                        "reused_from field. Meant for SAME-WINDOW composition"
                        " (measure_hw phase 5 merges fresh superstep rows "
                        "with the phase-1 artifact so the promotion gate "
                        "sees one complete same-chip sweep)")
    a = p.parse_args(argv)
    epochs = a.epochs if a.epochs is not None else (5 if a.quick else 50)
    if epochs < 1:
        p.error("--epochs must be >= 1")

    def measure(label, extra):
        rec, err = run_variant(extra, epochs)
        if rec is None:
            print(f"  {label}: FAILED {err}", file=sys.stderr)
            # same key schema as success rows (null-valued) so artifact
            # consumers can index/diff rows uniformly across rounds
            return {"label": label, "argv": extra, "value": None,
                    "unit": None, "vs_baseline": None, "tflops": None,
                    "mfu_vs_197t_bf16": None, "error": err}
        pf = perf_fields(rec["value"])
        print(f"  {label}: {rec['value']:,.0f} img/s/chip", file=sys.stderr)
        return {"label": label, "argv": extra, "value": rec["value"],
                "unit": rec["unit"], "vs_baseline": rec["vs_baseline"],
                "tflops": pf["tflops"],
                "mfu_vs_197t_bf16": pf["mfu_pct_vs_bf16_peak"]}

    base_rows, base_provenance = {}, {}
    if a.base:
        with open(a.base) as f:
            base_artifact = json.load(f)
        base_rows = {r["label"]: r
                     for r in base_artifact["variants"]
                     if r.get("value") is not None}
        # Reused rows carry the BASE run's identity inline (ADVICE r5 #3):
        # the merged artifact's top-level timestamp/backend describe THIS
        # run, while a reused row was measured under the base's — an
        # hour-plus gap inside one hardware window. Stamping both onto the
        # row keeps the promotion gate's "one window, one chip" premise
        # auditable from the artifact alone, without chasing reused_from.
        base_provenance = {
            "reused_from": a.base,
            "base_timestamp": base_artifact.get("timestamp"),
            "base_backend": base_artifact.get("backend"),
            "base_device_kind": base_artifact.get("device_kind"),
            "base_jax_version": base_artifact.get("jax_version"),
        }

    def skipped(label, extra):
        why = (f"--only {a.only!r}" if a.only is not None
               and a.only.lower() not in label.lower() else
               f"--skip {a.skip!r}")
        if label in base_rows:
            print(f"  {label}: reused from {a.base}", file=sys.stderr)
            return {**base_rows[label], **base_provenance}
        print(f"  {label}: SKIPPED ({why})", file=sys.stderr)
        return {"label": label, "argv": extra, "value": None,
                "unit": None, "vs_baseline": None, "tflops": None,
                "mfu_vs_197t_bf16": None,
                "error": [f"skipped by {why}"]}

    def wanted(label):
        if a.only is not None and a.only.lower() not in label.lower():
            return False
        return a.skip is None or a.skip.lower() not in label.lower()

    rows = [measure(label, extra) if wanted(label) else skipped(label, extra)
            for label, extra in VARIANTS]

    # A tunneled backend can drop mid-sweep and recover (each variant is its
    # own subprocess with bench.py's bounded startup retry); give failed rows
    # fresh passes at the end rather than losing them from the artifact.
    # (Skipped rows are deliberate absences, not failures — never retried.)
    for attempt in range(a.retries):
        failed = [i for i, r in enumerate(rows)
                  if r["value"] is None and wanted(r["label"])]
        if not failed:
            break
        print(f"retry pass {attempt + 1}/{a.retries}: "
              f"{len(failed)} failed variant(s)", file=sys.stderr)
        for i in failed:
            rows[i] = measure(rows[i]["label"], rows[i]["argv"])

    if a.out:
        import datetime
        info = _backend_info()
        # One statics stamp per MATRIX, not per cell (cells run with
        # PDMT_STATICS_STAMP=0). The audit traces example arrays, so it
        # needs the live backend the info probe just verified — a
        # backendless matrix (probe error recorded in `info`) keeps its
        # artifact and simply lacks the stamp, the same degradation rule
        # as the probe itself.
        statics = None
        if info.get("backend"):
            from bench import statics_stamp_fields
            statics = statics_stamp_fields()
        from bench import ledger_stamp_fields
        artifact = {"timestamp": datetime.datetime.now(
                        datetime.timezone.utc).isoformat(timespec="seconds"),
                    "epochs_per_window": epochs,
                    **info,
                    **({"statics": statics} if statics is not None else {}),
                    **ledger_stamp_fields(),
                    "variants": rows}
        with open(a.out, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
        print(f"wrote {a.out}", file=sys.stderr)

    print("\n| Variant | images/sec/chip | TFLOP/s | MFU (vs 197T bf16 peak) |")
    print("|---|---|---|---|")
    for r in rows:
        if r["value"] is None:
            word = ("skipped" if any("skipped by --" in e
                                     for e in r.get("error") or [])
                    else "failed")
            print(f"| {r['label']} | ({word}) | — | — |")
            continue
        print(f"| {r['label']} | {r['value']:,.0f} | {r['tflops']:.2f} "
              f"| {r['mfu_vs_197t_bf16']:.2f}% |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
