#!/usr/bin/env python
"""Elastic-training smoke: kill a rank mid-run, watch the survivors
shrink the world without losing the run, then grow it back.

    python scripts/elastic_smoke.py [--world 2] [--workdir DIR] ...

The front door of docs/ROBUSTNESS.md §Elastic training
(`make elastic-smoke`). At world >= 2, the full seeded shrink/grow cycle:

  1. SHRINK — a `--parallel --elastic --journal` world trains with
     `PDMT_FAULT=kill:rank=1:step=K`: rank 1 SIGKILLs itself mid-run,
     rank 0's next collective surfaces the peer loss, and the coordinator
     (elastic/coordinator.py) rescue-checkpoints, collects the beacon
     membership, and re-execs rank 0 into a WORLD-1 run under generation
     1 — which finishes every epoch. The loss curve printed across the
     whole cycle (one stdout: execv keeps the pipe) must be CONTINUOUS:
     every epoch logged exactly once, finite, trending down.
  2. JOURNAL — `trace report --cluster` over the survivor's telemetry
     proves the POST-reshape collective schedule: a clean world-1
     journal (no desync, collectives recorded) written by the re-exec'd
     generation.
  3. GROW — capacity returns: the full world is relaunched
     (scheduler-initiated, as documented) with `--resume <steps dir>
     --elastic` and more epochs under PDMT_ELASTIC_GEN=2. The world-1
     manifest re-maps UP (`--reshape global_batch`: same global batch,
     smaller per-device micro-batch) and the newest manifest must carry
     the grown geometry stamp (devices=world, elastic_gen=2).
  4. GATE — `check_telemetry --require elastic.,cluster.` over the
     cycle's telemetry.

World-1 fallback (this jaxlib has no CPU multiprocess collectives —
exit 75 at world >= 2, the chaos_smoke convention; `make elastic-smoke`
reruns with --world 1 automatically):

  A. RESHAPE MATH — process-free: the residual fold/drop and offset
     re-mapping semantics, straight against elastic/reshape.py (column
     sums preserved on fold, per_rank drops, grow appends zeros).
  B. KILL/RESUME-WITH-RESHAPE — a 1-process `--parallel --elastic` run
     is SIGKILLed at a seeded step and resumed with `--reshape per_rank`
     at a DIFFERENT batch size: the geometry change is re-mapped instead
     of refused, the loss curve stays continuous across the cycle, the
     journal proves the post-reshape schedule, and the elastic.,cluster.
     metric families gate.
  C. FORGED SHRINK — the newest manifest is re-stamped as a 2-device
     world's (devices=2, doubled global_batch) and resumed at 1 device
     under `--reshape global_batch`: the pre-pass must derive the
     micro-batch from the manifest and log the 2 -> 1 re-mapping.

Exit codes: 0 = every leg held; 1 = any leg failed; 75 = skipped (no CPU
multiprocess collectives; rerun with --world 1).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EPOCH_RE = re.compile(r"^Epoch=(\d+), train_loss=([0-9.eE+-]+)")


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(rank: int, port: int, argv, world: int, extra_env=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "WORLD_SIZE": str(world),
        "RANK": str(rank),
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train", *argv],
        cwd=REPO, env=env, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _run_serial(argv, timeout: float, extra_env=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    })
    env.update(extra_env or {})
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytorch_ddp_mnist_tpu.cli.train", *argv],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout)
        return r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        return None, e.stdout or "", e.stderr or ""


def _tool(args, timeout=120.0):
    return subprocess.run(
        [sys.executable, *args], cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PYTHONPATH=REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")))


def _epoch_curve(*stdouts):
    """(epoch, train_loss) pairs parsed from the machine-readable epoch
    lines, in print order across the given streams."""
    curve = []
    for out in stdouts:
        for line in out.splitlines():
            m = _EPOCH_RE.match(line.strip())
            if m:
                curve.append((int(m.group(1)), float(m.group(2))))
    return curve


def _continuous(curve, epochs: int):
    """The loss-curve continuity verdict: every epoch 0..epochs-1 logged
    (a re-exec may replay the interrupted epoch — duplicates allowed,
    gaps are not), every loss finite, and the curve trending down (last
    strictly below first). Returns None when continuous, else a reason."""
    if not curve:
        return "no epoch lines found"
    seen = {e for e, _ in curve}
    missing = sorted(set(range(epochs)) - seen)
    if missing:
        return f"epochs {missing} never logged (curve: {curve})"
    losses = [ls for _, ls in curve]
    if not all(ls == ls and ls != float("inf") for ls in losses):
        return f"non-finite loss in the curve: {curve}"
    if losses[-1] >= losses[0]:
        return (f"loss did not trend down across the cycle: "
                f"{losses[0]} -> {losses[-1]}")
    return None


def _newest_manifest(steps_dir: str):
    names = sorted(n for n in os.listdir(steps_dir)
                   if n.startswith("step_") and n.endswith(".json"))
    if not names:
        return None, None
    path = os.path.join(steps_dir, names[-1])
    with open(path) as f:
        return path, json.load(f)


def _journal_report(tel_dir: str, world: int):
    """trace report --cluster must show a CLEAN post-reshape schedule:
    `world` ranks, zero desync, collectives actually recorded. Returns
    None when it does, else a reason."""
    rep = _tool(["-m", "pytorch_ddp_mnist_tpu", "trace", "report",
                 "--cluster", "--json", tel_dir])
    if rep.returncode != 0:
        return f"trace report rc={rep.returncode}\n{rep.stdout}\n{rep.stderr}"
    report = json.loads(rep.stdout)
    if report["n_ranks"] != world:
        return f"journal shows {report['n_ranks']} rank(s), expected {world}"
    if not report["desync"]["ok"]:
        return f"post-reshape journal desync: {json.dumps(report['desync'])}"
    if report["totals"]["collectives"] == 0:
        return "post-reshape journal recorded no collectives"
    return None


# -- world-1 fallback legs --------------------------------------------------

def _reshape_math_leg():
    """Process-free: the fold/drop/grow semantics straight against
    elastic/reshape.py (the same rules tests/test_elastic.py pins)."""
    sys.path.insert(0, REPO)
    import numpy as np
    from pytorch_ddp_mnist_tpu.elastic import (plan_reshape, remap_offset,
                                               remap_residual)
    resid = np.arange(12, dtype=np.float32).reshape(4, 3)
    # shrink 4 -> 2, global batch preserved: rows fold j -> j % 2,
    # column sums exact
    plan = plan_reshape(64, 4, 2, mode="global_batch")
    out, disp = remap_residual(resid, plan)
    if disp != "folded" or out.shape != (2, 3):
        return f"fold disposition wrong: {disp} {out.shape}"
    if not np.array_equal(out.sum(axis=0), resid.sum(axis=0)):
        return "fold does not preserve column sums"
    if remap_offset(7, plan) != 7:
        return "global_batch mode must preserve the offset"
    # per_rank: residual dropped, offset floor-rescaled by samples
    plan = plan_reshape(64, 4, 2, mode="per_rank", per_device_batch=16)
    out, disp = remap_residual(resid, plan)
    if out is not None or disp != "dropped":
        return f"per_rank must drop the residual: {disp}"
    if remap_offset(7, plan) != 7 * 64 // 32:
        return "per_rank offset must floor-rescale by samples consumed"
    # grow 2 -> 4: surviving rows kept, new rows zero
    plan = plan_reshape(64, 2, 4, mode="global_batch")
    out, disp = remap_residual(resid[:2], plan)
    if disp != "grown_zeros" or out.shape != (4, 3):
        return f"grow disposition wrong: {disp} {out.shape}"
    if not (np.array_equal(out[:2], resid[:2]) and not out[2:].any()):
        return "grow must keep surviving rows and zero the new ones"
    return None


def _fallback_cycle_leg(work: str, timeout: float):
    """Legs B + C of the module docstring: seeded kill -> resume with
    `--reshape per_rank` at a different batch -> forged 2-device manifest
    resumed under `--reshape global_batch`. Returns (ok, detail)."""
    limit, batch, epochs, every = 256, 32, 3, 2
    kill_step = 11
    ckpt = os.path.join(work, "el.msgpack")
    steps_dir = ckpt + ".steps"
    t_kill = os.path.join(work, "t_kill")
    t_resume = os.path.join(work, "t_resume")
    base = ["--parallel", "--elastic", "--journal", "--kernel", "xla",
            "--limit", str(limit), "--lr", "0.1",
            "--path", os.path.join(work, "data"),
            "--checkpoint", ckpt, "--ckpt_every_steps", str(every)]
    # kill run: batch 32
    rc, out1, err1 = _run_serial(
        base + ["--n_epochs", str(epochs), "--batch_size", str(batch),
                "--telemetry", t_kill], timeout,
        extra_env={"PDMT_FAULT": f"kill:step={kill_step}"})
    if rc != -9:
        return False, f"kill run rc={rc}, expected SIGKILL (-9)\n{err1}"
    if not os.path.isdir(steps_dir) or not os.listdir(steps_dir):
        return False, f"no step checkpoints under {steps_dir}"
    # resume run: batch 16 under per_rank — geometry re-mapped, not refused
    rc, out2, err2 = _run_serial(
        base + ["--n_epochs", str(epochs), "--batch_size", "16",
                "--reshape", "per_rank", "--resume", steps_dir,
                "--telemetry", t_resume], timeout)
    if rc != 0:
        return False, f"reshape resume rc={rc}\n{out2}\n{err2}"
    if "[elastic] reshaped checkpoint geometry (per_rank)" not in err2:
        return False, f"resume printed no reshape line\n{err2}"
    bad = _continuous(_epoch_curve(out1, out2), epochs)
    if bad:
        return False, f"loss-curve continuity: {bad}"
    bad = _journal_report(t_resume, world=1)
    if bad:
        return False, bad
    chk = _tool([os.path.join(REPO, "scripts", "check_telemetry.py"),
                 "--require", "elastic.,cluster.", t_resume])
    if chk.returncode != 0:
        return False, (f"check_telemetry --require elastic.,cluster.:\n"
                       f"{chk.stdout}\n{chk.stderr}")
    # leg C: forge the newest manifest as a 2-device world's and resume
    # under global_batch — the pre-pass must derive micro-batch 32 (=64/1
    # per device... the manifest's doubled global batch over 1 device)
    # and log the 2 -> 1 residual-free shrink re-map
    mpath, rec = _newest_manifest(steps_dir)
    if rec is None:
        return False, f"no manifest to forge under {steps_dir}"
    old_gb = int(rec.get("meta", {}).get("global_batch", 16))
    rec.setdefault("meta", {})["global_batch"] = old_gb * 2
    rec["meta"]["devices"] = 2
    with open(mpath, "w") as f:
        json.dump(rec, f)
    rc, out3, err3 = _run_serial(
        base + ["--n_epochs", str(epochs + 1), "--batch_size", "999",
                "--resume", steps_dir,
                "--telemetry", os.path.join(work, "t_grow")], timeout)
    if rc != 0:
        return False, f"forged-shrink resume rc={rc}\n{out3}\n{err3}"
    if (f"global_batch={old_gb * 2}" not in out3
            or "devices 2 -> 1" not in err3):
        return False, (f"forged 2-device manifest was not re-mapped "
                       f"(expected global_batch={old_gb * 2}, "
                       f"'devices 2 -> 1')\n{out3}\n{err3}")
    return True, {"kill_step": kill_step,
                  "reshape": "per_rank then global_batch",
                  "forged_global_batch": old_gb * 2}


# -- the real shrink/grow cycle (world >= 2) --------------------------------

def _shrink_grow_cycle(work: str, world: int, timeout: float):
    """Legs 1-4 of the module docstring. Returns (ok, detail)."""
    limit, batch, epochs, every, kill_step = 512, 32, 4, 2, 9
    ckpt = os.path.join(work, "el.msgpack")
    steps_dir = ckpt + ".steps"
    telemetry = os.path.join(work, "telemetry")
    base = ["--parallel", "--elastic", "--journal", "--kernel", "xla",
            "--wireup_method", "env", "--limit", str(limit),
            "--batch_size", str(batch), "--lr", "0.1",
            "--path", os.path.join(work, "data"),
            "--checkpoint", ckpt, "--ckpt_every_steps", str(every),
            "--telemetry", telemetry]
    # 1. SHRINK: rank 1 killed; rank 0 reacts and re-execs to world 1
    port = _free_port()
    fault = f"kill:rank=1:step={kill_step}"
    procs = [_spawn(r, port, base + ["--n_epochs", str(epochs)], world,
                    {"PDMT_FAULT": fault,
                     # fast hang detection for the smoke
                     "PDMT_COLLECTIVE_HANG_S": "20",
                     "PDMT_ELASTIC_SETTLE_S": "2"})
             for r in range(world)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, err = p.communicate()
            outs.append((None, out, err))
    rc0, out0, err0 = outs[0]
    rc1 = outs[1][0]
    if rc1 != -9:
        return False, f"killed rank rc={rc1}, expected SIGKILL (-9)"
    if rc0 != 0:
        return False, (f"survivor rc={rc0} — the shrink cycle did not "
                       f"complete\n{out0}\n{err0}")
    if "[elastic] re-wiring: rank 0 -> 0 of 1" not in err0:
        return False, f"survivor printed no re-wire line\n{err0}"
    bad = _continuous(_epoch_curve(out0), epochs)
    if bad:
        return False, f"shrink loss-curve continuity: {bad}"
    # 2. JOURNAL: the post-reshape (world-1) schedule is clean
    bad = _journal_report(telemetry, world=1)
    if bad:
        return False, bad
    # 3. GROW: scheduler relaunches the full world with more epochs
    port = _free_port()
    grow_epochs = epochs + 2
    procs = [_spawn(r, port, base + ["--n_epochs", str(grow_epochs),
                                     "--resume", steps_dir], world,
                    {"PDMT_ELASTIC_GEN": "2"})
             for r in range(world)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, err = p.communicate()
            outs.append((None, out, err))
    if any(rc != 0 for rc, _, _ in outs):
        return False, "\n".join(
            f"grow rank {r} rc={rc}\n{o}\n{e}"
            for r, (rc, o, e) in enumerate(outs))
    bad = _continuous(_epoch_curve(out0, outs[0][1]), grow_epochs)
    if bad:
        return False, f"grow loss-curve continuity: {bad}"
    _, rec = _newest_manifest(steps_dir)
    meta = (rec or {}).get("meta", {})
    if meta.get("devices") != world or meta.get("elastic_gen") != 2:
        return False, (f"grown manifest not stamped with the new "
                       f"geometry/generation: {meta}")
    # 4. GATE
    chk = _tool([os.path.join(REPO, "scripts", "check_telemetry.py"),
                 "--require", "elastic.,cluster.", telemetry])
    if chk.returncode != 0:
        return False, (f"check_telemetry --require elastic.,cluster.:\n"
                       f"{chk.stdout}\n{chk.stderr}")
    return True, {"kill_step": kill_step, "epochs": epochs,
                  "grow_epochs": grow_epochs, "generations": [0, 1, 2]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="elastic shrink/grow smoke (kill a rank, keep the run)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=420.0)
    ap.add_argument("--keep_workdir", action="store_true")
    a = ap.parse_args(argv)

    # CPU multiprocess collectives need jax >= 0.5 (the chaos_smoke /
    # cluster_smoke gate): absent capability = skip signal 75, and the
    # Makefile reruns at --world 1.
    import jax
    if (a.world > 1
            and tuple(int(x)
                      for x in jax.__version__.split(".")[:2]) < (0, 5)):
        print("elastic_smoke: SKIP — this jaxlib has no CPU multiprocess "
              "collectives (needs jax >= 0.5)", file=sys.stderr)
        return 75

    work = a.workdir or tempfile.mkdtemp(prefix="pdmt_elastic_")
    os.makedirs(work, exist_ok=True)

    if a.world > 1:
        ok, detail = _shrink_grow_cycle(work, a.world, a.timeout)
        if not ok:
            print(f"elastic_smoke: FAIL in shrink/grow cycle — {detail}",
                  file=sys.stderr)
            return 1
        print(json.dumps({"elastic_smoke": "ok", "world": a.world,
                          "cycle": detail}))
    else:
        bad = _reshape_math_leg()
        if bad:
            print(f"elastic_smoke: FAIL in reshape-math leg — {bad}",
                  file=sys.stderr)
            return 1
        ok, detail = _fallback_cycle_leg(work, a.timeout)
        if not ok:
            print(f"elastic_smoke: FAIL in kill/resume-with-reshape leg — "
                  f"{detail}", file=sys.stderr)
            return 1
        print(json.dumps({"elastic_smoke": "ok", "world": 1,
                          "reshape_math": "ok", "cycle": detail}))
    if not a.keep_workdir and a.workdir is None:
        shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
