#!/usr/bin/env python
"""Live-health smoke: one command proving the watchdog round trip.

Launches a short `--cached` CPU training run with

  * an injected `nan:step=K` fault (utils/faultpoints.py) poisoning the
    reported loss at step K,
  * `--health checkpoint-and-warn` + step checkpoints every C steps,
  * `--telemetry DIR` and `--metrics_port 0` (ephemeral),

and asserts the three promises of the live-health layer round-trip:

  1. DURING the run, `GET /metrics` answers Prometheus text format
     covering the unified registry plus the `health_*` gauges (the live
     pull endpoint actually serves while training runs);
  2. the JSONL trace carries a schema-valid `health` event trail — the
     fatal `nan` detection — and the final registry snapshot carries the
     `health.*` metrics (`scripts/check_telemetry.py --require health.`);
  3. the step-checkpoint directory holds an INTACT checkpoint at a
     PRE-NaN step (the checkpoint-and-warn rescue): CRC-verified,
     decodable, every parameter finite.

Exit 0 on success; nonzero with the failed promise named on stderr.
`make health-smoke` is the committed entry point (JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import http.client
import json
import glob
import os
import subprocess
import sys
import tempfile
import time
import urllib.request
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NAN_STEP = 6          # poison the step-6 loss...
CKPT_EVERY = 4        # ...so the chunk-4 boundary state is the rescue


def fail(why: str, proc_out: str = "") -> "NoReturn":  # noqa: F821
    print(f"health_smoke: FAIL — {why}", file=sys.stderr)
    if proc_out:
        print(proc_out, file=sys.stderr)
    raise SystemExit(1)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="pdmt_health_smoke_")
    obs = os.path.join(tmp, "obs")
    ckpt = os.path.join(tmp, "model.msgpack")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    cmd = [sys.executable, "-m", "pytorch_ddp_mnist_tpu", "train",
           "--cached", "--epochs", "2", "--limit", "512",
           "--batch_size", "64", "--path", os.path.join(tmp, "nodata"),
           "--checkpoint", ckpt, "--ckpt_every_steps", str(CKPT_EVERY),
           # default --ckpt_keep on purpose: the rescue save is PINNED, so
           # it must survive the later routine saves' keep-last-N rotation
           "--health", "checkpoint-and-warn",
           "--fault", f"nan:step={NAN_STEP}",
           "--telemetry", obs, "--metrics_port", "0"]
    proc = subprocess.Popen(cmd, cwd=tmp, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)

    # -- 1. live /metrics scrape -----------------------------------------
    # The CLI prints `metrics on http://HOST:PORT/metrics` (stderr) before
    # training starts; scrape as soon as it appears — mid-run by
    # construction, since training hasn't finished warmup by then.
    # select() guards every read: a trainer that wedges pre-announcement
    # with stderr open (the hung-backend-init mode) must fail the smoke at
    # the deadline, not hang it forever in a blocking readline().
    import select
    stderr_lines = []
    url = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stderr], [], [],
                                    max(0.0, deadline - time.monotonic()))
        if not ready:
            break
        line = proc.stderr.readline()
        if not line:
            break
        stderr_lines.append(line)
        if line.startswith("metrics on "):
            url = line.split("metrics on ", 1)[1].strip()
            break
    if url is None:
        proc.kill()
        fail("the CLI never announced its --metrics_port endpoint "
             "within 120s", "".join(stderr_lines))
    try:
        body = urllib.request.urlopen(url, timeout=10).read().decode()
    except (OSError, ValueError, http.client.HTTPException) as e:
        # URLError/timeout are OSErrors; a half-started endpoint dropping
        # mid-response raises http.client (BadStatusLine/IncompleteRead)
        proc.kill()
        fail(f"live scrape of {url} failed: {e}")
    for needle in ("# TYPE", "health_worst_severity_level"):
        if needle not in body:
            proc.kill()
            fail(f"live /metrics scrape lacks {needle!r}:\n{body[:500]}")
    print(f"health_smoke: live scrape OK ({len(body.splitlines())} lines "
          f"from {url})")

    out, err = proc.communicate(timeout=600)
    transcript = err + "".join(stderr_lines) + out
    if proc.returncode != 0:
        fail(f"training run exited rc={proc.returncode} (checkpoint-and-"
             f"warn must keep the run alive)", transcript)

    # -- 2. the health event trail ---------------------------------------
    rc = subprocess.call([sys.executable,
                          os.path.join(REPO, "scripts", "check_telemetry.py"),
                          "--require", "health.", obs], env=env)
    if rc != 0:
        fail(f"check_telemetry --require health. exited {rc}")
    events = []
    with open(os.path.join(obs, "events.jsonl")) as f:
        for raw in f:
            rec = json.loads(raw)
            if rec.get("kind") == "point" and rec.get("name") == "health":
                events.append(rec["attrs"])
    nans = [e for e in events if e["detector"] == "nan"]
    if not nans or nans[0]["severity"] != "fatal":
        fail(f"no fatal 'nan' health event in the trace; saw {events}")
    print(f"health_smoke: health event trail OK ({len(events)} event(s), "
          f"nan detected at step {nans[0].get('step')})")

    # -- 3. the pre-NaN rescue checkpoint --------------------------------
    from flax import serialization
    import numpy as np
    steps_dir = ckpt + ".steps"
    pre_nan = []
    for man_path in sorted(glob.glob(os.path.join(steps_dir, "*.json"))):
        with open(man_path) as f:
            man = json.load(f)
        if man["step"] < NAN_STEP:
            pre_nan.append((man_path, man))
    if not pre_nan:
        fail(f"no pre-NaN (< step {NAN_STEP}) checkpoint under {steps_dir}; "
             f"have {os.listdir(steps_dir) if os.path.isdir(steps_dir) else 'no dir'}")
    man_path, man = pre_nan[-1]
    with open(os.path.join(steps_dir, man["payload"]), "rb") as f:
        blob = f.read()
    if len(blob) != man["bytes"] or zlib.crc32(blob) != man["crc32"]:
        fail(f"pre-NaN checkpoint {man_path} failed its size/CRC check")
    params = serialization.msgpack_restore(blob)
    bad = [k for k, v in _flat(params)
           if not np.isfinite(np.asarray(v)).all()]
    if bad:
        fail(f"pre-NaN checkpoint {man_path} holds non-finite leaves: {bad}")
    print(f"health_smoke: OK — intact finite rescue checkpoint at step "
          f"{man['step']} (< nan step {NAN_STEP}), "
          f"{len(events)} health event(s), live scrape served")
    return 0


def _flat(tree, prefix=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _flat(v, f"{prefix}/{k}")
    else:
        yield prefix, tree


if __name__ == "__main__":
    sys.exit(main())
