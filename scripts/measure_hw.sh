#!/bin/bash
# One-stop hardware measurement pass for a (possibly flaky) TPU session.
#
# Waits for the accelerator backend to answer (the tunneled TPU drops for
# multi-hour stretches and can HANG probes — docs/PERF.md), then runs, in
# priority order so a short window still captures the most valuable data:
#   0. ONE flagless headline bench (the driver's metric, ~60 s)
#   1. the bench variant matrix minus superstep rows -> $1
#      (default bench_matrix_hw.json) + the bf16 promotion gate
#      (phase 1b, informational)
#   2. inference throughput (--mode eval) + 10-epoch accuracy parity
#      (--mode accuracy, the north-star semantics check)
#   3. the Mosaic hardware test suite  (PDMT_TPU_TESTS=1)
#   4. the superstep / bf16 / batch-scaling sweep: the r05 window's
#      outage began mid-superstep-8-row and the kernel could not be
#      cleared of wedging the chip — everything wedge-suspect runs after
#      the data we can't afford to lose.
#   5. IF the sweep cleared every superstep config: measure JUST the
#      superstep matrix rows, merge with phase 1's rows (same window/chip,
#      bench_matrix --base) -> ${1%.json}_full.json + the gate on it, so
#      an unattended window can still promote a superstep win.
#
# Every phase's exit status is tracked: the script exits nonzero with a
# per-phase summary if ANY phase failed, so a caller keying on the exit
# code can never mistake a dead-tunnel pass for a complete one (ADVICE r3).
#
# Usage:  scripts/measure_hw.sh [matrix_out.json]
#   PDMT_WINDOW_WAIT  seconds to keep polling for the backend before giving
#                     up (default 1800; each probe is a fresh 45 s-bounded
#                     subprocess, immune to the hang-mode outage)
set -u
cd "$(dirname "$0")/.."
OUT="${1:-bench_matrix_hw.json}"
WAIT="${PDMT_WINDOW_WAIT:-1800}"

deadline=$((SECONDS + WAIT))
until timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; do
  if ((SECONDS >= deadline)); then
    echo "measure_hw: backend still unavailable after ${WAIT}s" >&2
    exit 1
  fi
  echo "measure_hw: backend down, retrying ($((deadline - SECONDS))s left)" >&2
  sleep 60
done
echo "measure_hw: backend up at $(date -u +%H:%M:%S)" >&2

declare -A status

# Priority order: the most valuable datum first — a window can close in
# minutes (docs/PERF.md outage log), and one flagless bench (~60 s) IS the
# driver's headline measurement.
echo "== phase 0: flagless headline bench" >&2
timeout 600 python bench.py --backend_wait 120
status[headline]=$?

echo "== phase 1: variant matrix (superstep rows deferred to phase 4) -> $OUT" >&2
python scripts/bench_matrix.py --epochs 400 --retries 2 --skip "superstep" \
  --out "$OUT"
status[matrix]=$?

# The config promotion gate — writes bench_calibration.json only if a
# bf16/superstep candidate beats the f32/K1 baseline in THIS matrix (bf16
# winners additionally pass the 10-epoch accuracy-parity run). rc=0/1 are
# the gate's two VERDICTS (promoted / not promoted — both fine); anything
# else (crash rc=2, timeout rc=124) is a tracked phase failure, not a
# losing candidate (ADVICE r4).
echo "== phase 1b: epoch-kernel config promotion gate" >&2
timeout 900 python scripts/promote_epoch_dtype.py --matrix "$OUT"
promote_rc=$?
status[promote]=0
if ((promote_rc == 0)); then
  echo "measure_hw: config PROMOTED (bench_calibration.json)" >&2
elif ((promote_rc == 1)); then
  echo "measure_hw: config not promoted (gate or matrix incomplete)" >&2
else
  echo "measure_hw: promotion gate FAILED rc=$promote_rc" >&2
  status[promote]=$promote_rc
fi

echo "== phase 2: inference throughput" >&2
timeout 600 python bench.py --backend_wait 120 --mode eval
status[eval]=$?

echo "== phase 2b: 10-epoch accuracy parity (north-star semantics)" >&2
timeout 900 python bench.py --backend_wait 120 --mode accuracy
status[accuracy]=$?

echo "== phase 3: Mosaic hardware suite" >&2
PDMT_TPU_TESTS=1 timeout 3600 python -u -m pytest tests/test_pallas_step.py -q
status[mosaic]=$?

# Wedge-suspect rows LAST (see header): batch scaling first (K=1, safe
# shapes), then superstep K ascending so a small-K wedge stops the sweep
# before the K=8 configuration that coincided with the r05 outage.
echo "== phase 4: batch-scaling + superstep sweep (wedge-suspect, last)" >&2
status[sweep]=0
for ARGS in "--dtype float32 --superstep 1 --batch_size 256" \
            "--dtype float32 --superstep 1 --batch_size 512" \
            "--dtype float32 --superstep 1 --batch_size 1024" \
            "--dtype float32 --superstep 2" \
            "--dtype float32 --superstep 4" \
            "--dtype bfloat16 --superstep 2" \
            "--dtype float32 --superstep 8" \
            "--dtype bfloat16 --superstep 8"; do
  # Cheap 5-epoch probe first: if a config hangs, it hangs HERE (300 s,
  # and the result says compile/launch, not scale — the r05 K=8 mystery);
  # only a clean probe earns the 400-epoch timed row.
  echo "pallas_epoch $ARGS (probe):" >&2
  if ! timeout 300 python bench.py --backend_wait 120 --epochs 5 \
       --kernel pallas_epoch $ARGS > /dev/null; then
    echo "measure_hw: probe failed/hung for '$ARGS' — skipping its timed row" >&2
    status[sweep]=1
    continue
  fi
  echo "pallas_epoch $ARGS:" >&2
  timeout 600 python bench.py --backend_wait 120 --kernel pallas_epoch $ARGS \
    || status[sweep]=$?
done

# Promotion needs superstep rows IN a matrix artifact (one sweep, one
# chip), but phase 1 skips them as wedge-suspect. Once the loose sweep
# above has run every superstep config without wedging the chip, measuring
# just the superstep rows is safe — merge them with phase 1's rows (same
# window, same chip: --base) and re-run the gate, so an unattended window
# can still promote a superstep win without re-measuring the 10 rows
# phase 1 already has.
echo "== phase 5: superstep matrix rows + gate (cleared by phase 4)" >&2
status[fullmatrix]=0
if ((status[sweep] == 0)); then
  FULL="${OUT%.json}_full.json"
  rm -f "$FULL"   # never let the gate read a previous window's artifact
  python scripts/bench_matrix.py --epochs 400 --retries 1 \
    --only superstep --base "$OUT" --out "$FULL"
  status[fullmatrix]=$?
  if ((status[fullmatrix] == 0)); then
    timeout 900 python scripts/promote_epoch_dtype.py --matrix "$FULL"
    full_rc=$?
    if ((full_rc == 0)); then
      echo "measure_hw: config PROMOTED from full matrix" >&2
    elif ((full_rc == 1)); then
      echo "measure_hw: full-matrix gate: not promoted" >&2
    else
      echo "measure_hw: full-matrix promotion gate FAILED rc=$full_rc" >&2
      status[fullmatrix]=$full_rc
    fi
  else
    echo "measure_hw: superstep matrix run failed rc=${status[fullmatrix]};" \
         " gate not run" >&2
  fi
else
  # Distinct nonzero rc: a skipped phase must never read as green in the
  # per-phase summary (rc=0 here would let a committed sweep log claim the
  # merged-matrix gate ran when it never did). 75 = EX_TEMPFAIL: rerunnable.
  status[fullmatrix]=75
  echo "measure_hw: skipping superstep matrix (sweep rc=${status[sweep]}" \
       " did not clear the superstep rows)" >&2
fi

fail=0
for phase in headline matrix promote eval accuracy mosaic sweep fullmatrix; do
  if ((status[$phase] == 75)); then
    echo "measure_hw: phase $phase rc=75 (skipped: prerequisite failed)" >&2
  else
    echo "measure_hw: phase $phase rc=${status[$phase]}" >&2
  fi
  ((status[$phase] != 0)) && fail=1
done
echo "measure_hw: done at $(date -u +%H:%M:%S) (fail=$fail)" >&2
exit $fail
