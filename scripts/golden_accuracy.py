#!/usr/bin/env python
"""Regenerate the 10-epoch accuracy-parity golden artifact.

The north-star acceptance (BASELINE.json; SURVEY.md §4 item 1) is
"identical 10-epoch test accuracy" vs the reference trainer
(/root/reference/ddp_tutorial_multi_gpu.py:100-116, final accuracy :127).
This script backs that claim with a checked-in artifact instead of a
30-step unit test: it trains the reference workload END-TO-END, twice —

  * in this framework (xla kernel, float32, threefry dropout: the
    reference-semantics configuration), and
  * in an independent torch re-statement of the reference model + loop
    (tests/test_torch_parity.py's model, extended to full training with
    dropout ACTIVE),

from the SAME initial weights (torch's init, exported), on the SAME data
(the deterministic synthetic MNIST stand-in — this environment is
zero-egress; pass --data_root to use real IDX files) in the SAME batch
order (ShardedSampler, seed 42).  Dropout masks are each side's native RNG
stream — exactly the reference's own situation across two seeds — so the
expected accuracy gap is run-to-run mask noise.  The script MEASURES that
noise by training torch twice more with different dropout seeds, then
asserts

    |acc_framework - acc_torch| <= max(NOISE_MULT * torch_spread, ACC_FLOOR)

and the analogous bound on mean val loss.  Writes the full per-epoch
curves + verdict to --out (committed as docs/golden_accuracy.json) and
exits nonzero on failure, so CI and a human get the same judgement.

Usage:
    python scripts/golden_accuracy.py                 # the 10-epoch artifact
    python scripts/golden_accuracy.py --epochs 1 --train_n 4096 \
        --test_n 1024 --out /tmp/golden_quick.json    # smoke (tests use this)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force the CPU backend BEFORE any framework import touches jax: the session
# may have a (possibly hanging, tunneled) TPU backend pre-registered at
# interpreter startup, and env vars alone don't drop it (tests/conftest.py
# documents the same dance). The golden run is a CPU artifact by design.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends
    clear_backends()
except (ImportError, AttributeError, RuntimeError):
    pass  # older jax spelling / nothing to clear: proceed on CPU anyway

# Gap thresholds: the accuracy bound is NOISE_MULT x the measured torch
# run-to-run spread, floored at max(ACC_FLOOR, ACC_FLOOR_SAMPLES/test_n) —
# the absolute floor covers the saturated regime (two-run spread can be ~0
# when every run lands on the same handful of residual errors) and the
# sample floor covers small test sets, where one flipped prediction moves
# accuracy by 1/test_n and a two-run spread badly underestimates the true
# run-to-run sigma. The val-loss ratio bound is fixed — loss is the
# continuous, sensitive signal either way.
NOISE_MULT = 3.0
ACC_FLOOR = 0.004
ACC_FLOOR_SAMPLES = 8.0
LOSS_RATIO_BOUND = 0.05


# The single shared torch re-statement of the reference model + weight
# conversion (also used by tests/test_torch_parity.py — one statement, so
# the golden artifact and the parity unit tests can never certify against
# different models).
from pytorch_ddp_mnist_tpu.utils.torch_ref import (build_reference_model,
                                                   params_from_torch)


def _torch_modules():
    import torch
    import torch.nn.functional as F
    return torch, None, F


def shared_batch_indices(n_train: int, epochs: int, batch: int) -> np.ndarray:
    """(E, nbatches, batch) int32 — the flagship sampler order (seed 42,
    reshuffled per epoch), identical for both trainers."""
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train.scan import epoch_batch_indices
    sampler = ShardedSampler(n_train, num_replicas=1, rank=0, shuffle=True,
                             seed=42)
    idxs = []
    for e in range(epochs):
        sampler.set_epoch(e)
        idxs.append(epoch_batch_indices(sampler, batch))
    return np.stack(idxs)


def train_torch(init_seed: int, dropout_seed: int, x_train: np.ndarray,
                y_train: np.ndarray, idxs: np.ndarray, x_test: np.ndarray,
                y_test: np.ndarray, lr: float) -> dict:
    """One full torch training run (dropout ACTIVE — the reference's
    nn.Dropout draws from torch's global CPU RNG, ddp_tutorial_cpu.py:47),
    evaluated on the full test set after every epoch."""
    torch, _, F = _torch_modules()
    model = build_reference_model(init_seed)
    torch.manual_seed(dropout_seed)  # the dropout stream, separate from init
    opt = torch.optim.SGD(model.parameters(), lr=lr)
    xt = torch.tensor(x_test)
    yt = torch.tensor(y_test.astype(np.int64))
    curve = []
    for epoch_idx in idxs:
        model.train()
        for b in epoch_idx:
            xb = torch.tensor(x_train[b])
            yb = torch.tensor(y_train[b].astype(np.int64))
            opt.zero_grad()
            loss = F.cross_entropy(model(xb), yb)
            loss.backward()
            opt.step()
        model.eval()
        with torch.no_grad():
            logits = model(xt)
            per_sample = F.cross_entropy(logits, yt, reduction="none")
            acc = (logits.argmax(1) == yt).float().mean()
        curve.append({"mean_val_loss": float(per_sample.mean()),
                      "accuracy": float(acc)})
    return {"init_seed": init_seed, "dropout_seed": dropout_seed,
            "curve": curve, "final_accuracy": curve[-1]["accuracy"],
            "final_mean_val_loss": curve[-1]["mean_val_loss"]}


def train_framework(params0, x_train_u8: np.ndarray, y_train: np.ndarray,
                    idxs: np.ndarray, x_test: np.ndarray, y_test: np.ndarray,
                    lr: float) -> dict:
    """The framework run: reference-semantics config (xla kernel, float32,
    threefry dropout stream), whole run as one fused program with per-epoch
    params snapshots, then one vmapped eval over the snapshots."""
    import jax
    import jax.numpy as jnp
    from pytorch_ddp_mnist_tpu.train.loop import (make_snapshot_eval_step,
                                                  val_summary)
    from pytorch_ddp_mnist_tpu.train.scan import make_run_fn, resident_images

    run = make_run_fn(lr, dtype="float32", kernel="xla", snapshots=True)
    _, _, losses, (p_snaps, _) = run(
        params0, jax.random.key(1, impl="threefry2x32"),
        jax.device_put(resident_images(x_train_u8)),
        jax.device_put(y_train.astype(np.int32)), jax.device_put(idxs))
    assert np.isfinite(np.asarray(losses)).all(), "non-finite training loss"
    per_sample, correct = make_snapshot_eval_step()(
        p_snaps, jnp.asarray(x_test), jnp.asarray(y_test.astype(np.int32)))
    per_sample, correct = np.asarray(per_sample), np.asarray(correct)
    curve = []
    for e in range(per_sample.shape[0]):
        _, mean_loss, acc = val_summary(per_sample[e], correct[e],
                                        batch_size=idxs.shape[-1])
        curve.append({"mean_val_loss": mean_loss, "accuracy": acc})
    return {"impl": "threefry2x32", "kernel": "xla", "dtype": "float32",
            "curve": curve, "final_accuracy": curve[-1]["accuracy"],
            "final_mean_val_loss": curve[-1]["mean_val_loss"]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--train_n", type=int, default=60000)
    ap.add_argument("--test_n", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--init_seed", type=int, default=7)
    ap.add_argument("--dropout_seeds", type=int, nargs=3,
                    default=(1234, 5678, 91011),
                    help="torch dropout streams: run A (the comparison run) "
                         "+ two noise-estimation reruns")
    ap.add_argument("--data_root", default=None,
                    help="directory with real MNIST IDX files; default: the "
                         "deterministic synthetic stand-in (zero-egress)")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "golden_accuracy.json"))
    a = ap.parse_args(argv)

    from pytorch_ddp_mnist_tpu.data import normalize_images, synthetic_mnist
    if a.data_root:
        from pytorch_ddp_mnist_tpu.data.mnist import load_mnist
        train, test = load_mnist(a.data_root, True), load_mnist(a.data_root, False)
        if train is None or test is None:
            raise SystemExit(f"--data_root {a.data_root}: IDX files not found")
        data_source = "mnist_idx"
    else:
        train = synthetic_mnist(a.train_n, seed=0)
        test = synthetic_mnist(a.test_n, seed=1)
        data_source = "synthetic"
    x_train = normalize_images(train.images)
    x_test = normalize_images(test.images)
    idxs = shared_batch_indices(len(train.images), a.epochs, a.batch)

    print(f"[golden] torch runs: 1 comparison + 2 noise "
          f"({len(train.images)} train rows, {a.epochs} epochs)", flush=True)
    torch_runs = [train_torch(a.init_seed, ds, x_train, train.labels, idxs,
                              x_test, test.labels, a.lr)
                  for ds in a.dropout_seeds]
    print("[golden] framework run", flush=True)
    fw = train_framework(params_from_torch(build_reference_model(a.init_seed)),
                         train.images, train.labels, idxs, x_test,
                         test.labels, a.lr)

    accs = [r["final_accuracy"] for r in torch_runs]
    losses = [r["final_mean_val_loss"] for r in torch_runs]
    noise_acc = max(accs) - min(accs)
    acc_bound = max(NOISE_MULT * noise_acc, ACC_FLOOR,
                    ACC_FLOOR_SAMPLES / len(test.images))
    acc_gap = abs(fw["final_accuracy"] - accs[0])
    loss_ratio = abs(fw["final_mean_val_loss"] - losses[0]) / max(losses[0], 1e-9)
    ok = acc_gap <= acc_bound and loss_ratio <= LOSS_RATIO_BOUND

    artifact = {
        "what": "10-epoch accuracy-parity golden run: this framework vs an "
                "independent torch re-statement of the reference trainer, "
                "same init/data/batch-order, native dropout streams",
        "reference": "ddp_tutorial_multi_gpu.py:100-116 (eval loop), :127 "
                     "(final accuracy print)",
        "config": {"epochs": a.epochs, "batch": a.batch, "lr": a.lr,
                   "train_n": len(train.images), "test_n": len(test.images),
                   "data": data_source, "sampler_seed": 42,
                   "init_seed": a.init_seed},
        "torch_runs": torch_runs,
        "framework_run": fw,
        "verdict": {
            "framework_final_accuracy": fw["final_accuracy"],
            "torch_final_accuracy": accs[0],
            "accuracy_gap": round(acc_gap, 6),
            "torch_run_to_run_spread": round(noise_acc, 6),
            "accuracy_bound": round(acc_bound, 6),
            "val_loss_ratio_gap": round(loss_ratio, 6),
            "val_loss_ratio_bound": LOSS_RATIO_BOUND,
            "pass": ok,
        },
    }
    with open(a.out, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    v = artifact["verdict"]
    print(f"[golden] framework acc={v['framework_final_accuracy']:.4f} "
          f"torch acc={v['torch_final_accuracy']:.4f} "
          f"gap={v['accuracy_gap']:.4f} (bound {v['accuracy_bound']:.4f}, "
          f"torch spread {v['torch_run_to_run_spread']:.4f}) "
          f"loss_ratio={v['val_loss_ratio_gap']:.4f} -> "
          f"{'PASS' if ok else 'FAIL'}")
    print(f"[golden] wrote {a.out}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
