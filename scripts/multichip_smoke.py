"""The multichip smoke, upgraded to a measured artifact (MULTICHIP_r0X.json).

Rounds 1-5 recorded only `{n_devices, rc, ok, skipped, tail}` — a smoke bit
saying the N-device SPMD programs compiled and ran. This script keeps those
keys (trend continuity: old consumers index them unchanged) and adds the
read side the DDP comms layer earned: one throughput row per
gradient-communication strategy (parallel/collectives.py via
bench.ddp_strategy_rows — the SAME measurement `bench.py --mode ddp`
emits), each with

    {strategy, n_devices, images_per_sec, scaling_efficiency_vs_1dev, ...}

Usage:
    python scripts/multichip_smoke.py --out MULTICHIP_r06.json          # real backend
    python scripts/multichip_smoke.py --fake 8 --out MULTICHIP_r06.json # CPU fakes

`--fake N` forces an N(+1 spare)-device virtual CPU pool BEFORE jax loads —
the same stand-in the driver's dry run uses; the artifact stamps the backend
so fake-device rows can never be mistaken for hardware numbers. The dry run
itself (`__graft_entry__.dryrun_multichip` — compile+run of every DP program
shape, now including the sharded/bf16 comm steps) executes in a SUBPROCESS
exactly like the driver runs it, and its rc/tail land in the old keys.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n_devices", type=int, default=8,
                   help="mesh size for the dry run (and, with --fake, the "
                        "virtual pool to create)")
    p.add_argument("--fake", type=int, default=None, metavar="N",
                   help="run on N virtual CPU devices (sets XLA_FLAGS + "
                        "JAX_PLATFORMS before jax loads) instead of the "
                        "session backend")
    p.add_argument("--out", default=None,
                   help="write the artifact JSON here (default: stdout)")
    p.add_argument("--epochs", type=int, default=3,
                   help="fused epochs per strategy timing window")
    p.add_argument("--batch_size", type=int, default=16,
                   help="per-chip batch for the strategy rows")
    p.add_argument("--model", choices=("mlp", "deep_mlp"), default="mlp",
                   help="model family for the strategy rows "
                        "(models/zoo.py)")
    p.add_argument("--param_scale", type=int, default=1,
                   help="hidden-width multiplier for the strategy rows — "
                        "the model-size axis (at 1 the 118k-param MLP is "
                        "dispatch-bound and comm strategies are noise; "
                        "ISSUE 7's acceptance measures >= 8)")
    p.add_argument("--overlap_rows", action="store_true",
                   help="additionally measure every strategy's "
                        "bucket-pipelined (overlap=True) variant — doubles "
                        "the row count")
    p.add_argument("--n_rows", type=int, default=2048,
                   help="synthetic training rows per epoch window (large "
                        "models amortize comm over this many images)")
    p.add_argument("--skip_rows", action="store_true",
                   help="dry run only — record the old smoke-bit keys with "
                        "an empty strategies list (a backendless window)")
    a = p.parse_args(argv)

    if a.fake:
        # Before ANY jax import: XLA parses XLA_FLAGS once, at first client
        # creation. +1 spare device — the dry run's TPU-semantics simulator
        # needs a free host worker (see __graft_entry__.dryrun_multichip).
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={a.fake + 1}"
        ).strip()
        a.n_devices = a.fake

    # The dry run, in a subprocess exactly like the driver invokes it —
    # its rc/ok/tail are the artifact's legacy smoke-bit keys. A hang
    # (the BENCH_r01-r05 tunnel failure mode) records rc=None/ok=false
    # instead of losing the artifact to an uncaught TimeoutExpired.
    try:
        dry = subprocess.run(
            [sys.executable, "-c",
             f"import __graft_entry__ as g; "
             f"g.dryrun_multichip({a.n_devices})"],
            cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
            timeout=1200)
        rc, out_text = dry.returncode, dry.stdout + dry.stderr
    except subprocess.TimeoutExpired as e:
        rc = None
        out_text = ((e.stdout or "") + (e.stderr or "")
                    if isinstance(e.stdout, str) or isinstance(e.stderr, str)
                    else "") + "\ndry run timed out after 1200s"
    tail = "\n".join(out_text.strip().splitlines()[-4:])

    artifact = {
        # legacy keys, kept verbatim for trend continuity with r01-r05
        "n_devices": a.n_devices,
        "rc": rc,
        "ok": rc == 0,
        "skipped": False,
        "tail": tail,
    }
    # The ledger stamp (telemetry/ledger.py): schema generation + run
    # ordinal, so future rounds append to the history instead of being
    # re-derived from the _rNN filename convention. Inlined (not imported
    # from bench.ledger_stamp_fields) so the failed-backend path never
    # imports jax; tests pin the two against ledger.SCHEMA_VERSION.
    artifact["schema_version"] = 2
    try:
        artifact["run_ord"] = int(os.environ.get("PDMT_RUN_ORD", ""))
    except ValueError:
        artifact["run_ord"] = int(time.time())

    rows = []
    if rc == 0 and not a.skip_rows:
        # A failed measurement must never cost the artifact: the dry run's
        # legacy smoke bit already passed, and the pre-upgrade script
        # always recorded it — so row errors land IN the artifact (the
        # bench_matrix null-row idiom), never as a lost traceback. The
        # usual cause: --n_devices larger than the real pool (the dry run
        # sizes its own fake pool in a subprocess, so it cannot catch it).
        sys.path.insert(0, str(REPO))
        import jax
        from bench import ddp_strategy_rows, statics_stamp_fields
        artifact["backend"] = jax.default_backend()
        artifact["device_kind"] = getattr(jax.devices()[0], "device_kind",
                                          str(jax.devices()[0]))
        artifact["jax_version"] = jax.__version__
        try:
            if jax.device_count() < a.n_devices:
                raise RuntimeError(
                    f"--n_devices {a.n_devices} exceeds the "
                    f"{jax.device_count()}-device pool (pass --fake "
                    f"{a.n_devices} for virtual CPU devices)")
            # n_devices pinned: with --fake the pool holds a +1 spare for
            # the dry run's simulator that must not join the measured mesh
            rows = ddp_strategy_rows(per_chip_batch=a.batch_size,
                                     epochs=a.epochs,
                                     n_devices=a.n_devices,
                                     n_rows=a.n_rows,
                                     model=a.model,
                                     param_scale=a.param_scale,
                                     overlap_variants=(
                                         (False, True) if a.overlap_rows
                                         else (False,)))
            artifact["model"] = a.model
            artifact["param_scale"] = a.param_scale
        except Exception as e:  # noqa: BLE001 — recorded, not raised
            print(f"multichip_smoke: strategy rows failed: {e}",
                  file=sys.stderr)
            artifact["strategies_error"] = str(e)[:500]
        # Same env-gated statics stamp as every bench.py artifact line
        # (the MULTICHIP JSON records whether the measured build honored
        # the static contracts) — OUTSIDE the rows try, so a stamp
        # problem can never be mislabeled a measurement failure; the
        # stamp itself degrades to null fields + error, never raises.
        statics = statics_stamp_fields()
        if statics is not None:
            artifact["statics"] = statics
    artifact["strategies"] = rows

    out = json.dumps(artifact, indent=2) + "\n"
    if a.out:
        with open(a.out, "w") as f:
            f.write(out)
        print(f"wrote {a.out}", file=sys.stderr)
    else:
        sys.stdout.write(out)
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
