#!/usr/bin/env python
"""Validate a telemetry JSONL event trace against the v1 schema.

    python scripts/check_telemetry.py /tmp/obs            # a --telemetry dir
    python scripts/check_telemetry.py events.jsonl        # or one file
    python scripts/check_telemetry.py --require ddp. DIR  # + metric gate:
        # fail unless the trace's registry snapshot carries at least one
        # metric per --require prefix (the ddp-smoke contract). Repeatable,
        # AND one --require takes a comma-separated prefix list —
        # `--require cluster.,ddp.` gates two metric families in ONE
        # invocation (smoke scripts used to chain one process per family)

Exit 0 when every `events*.jsonl` is schema-valid; nonzero (with one line
per violation on stderr) on malformed JSON, unknown schema version or kind,
missing required fields, malformed `health` events (a health point must
carry a non-empty string detector and a known severity —
`--require health.` gates on the watchdog's registry metrics being present,
the obs/ddp-smoke pattern), OUT-OF-ORDER records (t_mono must be
non-decreasing within a run segment — the writer stamps emission time
exactly so this holds; an appended file holds one segment per
`trace_start` record), negative span durations, or span-STRUCTURE
violations: parent references that never appear in their segment, duplicate
span ids, a recorded exit with no matching enter (t0_mono + dur_s past the
emission stamp), and child spans crossing their parent's interval. Serve
traces get the request/batch contract on top (`serve.request` spans must
carry a non-empty `request_id`, their `batch` link must resolve to a real
`serve.batch` span in the segment, and a batch's stage children must start
in pipeline order — use `--require serve.` to also gate on the serve
registry metrics, the serve-trace-smoke pattern). The
structural checks are the span-tree reconstructor shared with
`pytorch_ddp_mnist_tpu/telemetry/analysis.py` (file-loaded, not
package-imported, so no framework import happens); when the analysis
module is not beside this script (a copied-alone checker), they degrade to
the orphaned-parent check with a stderr note. `program_cost` point records
(the `trace cost` harvest, telemetry/costs.py) get their own shared
contract: a non-empty string `program` label and non-negative byte/flop
fields — `--require xla.` / `--require mem.` gate the compile metrics and
HBM watermark gauges being present (the cost-smoke pattern), with the same
named degrade when analysis.py predates `cost_record_errors`.
`dispatch_phase` / `dispatch_window` point records (telemetry/dispatch.py
epoch flushes, the `trace report --overhead` input) get the dispatch
record contract the same way: a known phase name, non-negative durations,
int step/epoch indices — `--require dispatch.` gates the profiler's
`dispatch.*` histograms being present (the overhead-smoke pattern), with
the same named degrade on an older analysis.py. `ledger_row` point
records (the performance ledger re-emitting its canonical rows,
`python -m pytorch_ddp_mnist_tpu ledger ... --telemetry DIR`) get the
ledger record contract the same way: a non-empty series key, a KNOWN
direction (higher_better/lower_better — the trend gate is meaningless
without one), a finite value — `--require ledger.` gates the
`ledger.series`/`ledger.regressions` registry metrics being present (the
ledger-smoke pattern). `fleet_event` / `reload_event` point records
(serve/fleet.py replica state transitions, serve/reload.py hot-reload
verdicts) get the fleet record contract the same way: known event names,
non-negative int replica indices, known quarantine causes, a non-empty
refusal reason, and the drain-before-swap invariant itself —
`outstanding_at_swap == 0` on every swapped event — with
`--require serve.fleet.,serve.reload.` gating the fleet counters and
reload gauges being present (the chaos-smoke pattern). Pure stdlib,
no jax import: the checker must run anywhere the trace lands, including
hosts without the framework installed.
"""

from __future__ import annotations

import glob
import importlib.util
import json
import os
import sys

SCHEMA_VERSION = 1
KINDS = ("meta", "span", "point", "snapshot")
REQUIRED = ("v", "kind", "name", "t_wall", "t_mono", "proc")
# `health` point records (telemetry/health.py watchdog): the detector and
# severity fields are the contract every reader keys on — a record that
# lost either is noise pretending to be signal, so the checker rejects it.
HEALTH_SEVERITIES = ("info", "warn", "fatal")
HEALTH_REQUIRED = ("detector", "severity")


def _load_analysis():
    """The shared span-tree reconstructor, loaded BY FILE PATH (the package
    __init__ imports jax via compat; the checker must stay framework-free).
    None when the module is not beside this script."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "pytorch_ddp_mnist_tpu", "telemetry", "analysis.py")
    if not os.path.exists(path):
        return None
    try:
        spec = importlib.util.spec_from_file_location(
            "_pdmt_trace_analysis", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception as e:  # a broken analysis.py must not mask the trace
        print(f"check_telemetry: note: could not load analysis.py "
              f"({e}); span-structure checks degrade to orphan detection",
              file=sys.stderr)
        return None


_analysis = _load_analysis()


def _fallback_structure_errors(segment):
    """Copied-alone degradation: orphaned-parent detection only (the
    original checker's guarantee). Parents close AFTER children, so ids
    resolve against the whole segment."""
    span_ids = {rec["span"] for rec in segment
                if rec.get("kind") == "span" and "span" in rec}
    errors = []
    for rec in segment:
        if rec.get("kind") != "span":
            continue
        parent = rec.get("parent")
        if parent is not None and parent not in span_ids:
            errors.append((rec.get("_line", 0),
                           f"parent span {parent} never recorded"))
    return errors


_degrade_noted: "set[str]" = set()   # print-once latches (per skipped check)


def _note_degraded(why: str, skipped: str) -> None:
    """One stderr line per degraded check, naming exactly what was
    skipped — a checker copied beside an older/missing analysis.py must
    say it degraded, or a partial copy masquerades as a full pass."""
    if skipped in _degrade_noted:
        return
    _degrade_noted.add(skipped)
    print(f"check_telemetry: note: {why}; skipping {skipped}",
          file=sys.stderr)


_SERVE_SKIP = ("the serve span contract (serve.request request_id, batch "
               "links resolving, pipeline-ordered batch stages)")
_COST_SKIP = ("the program_cost record contract (non-empty program label, "
              "non-negative byte/flop fields)")
_DISPATCH_SKIP = ("the dispatch record contract (known phase name, "
                  "non-negative durations, int step/epoch indices)")
_LEDGER_SKIP = ("the ledger_row record contract (non-empty series key, "
                "known direction, finite value)")
_FLEET_SKIP = ("the fleet/reload record contract (known event names, "
               "outstanding_at_swap == 0 on swaps, named refusals)")


def span_structure_errors(segment):
    if _analysis is not None:
        errors = list(_analysis.span_structure_errors(segment))
        # the serve request/batch span contract (serve/tracing.py):
        # non-empty request_id, batch links resolving to a real
        # serve.batch span, pipeline-ordered batch stages. hasattr-guarded
        # so this checker still runs beside an older analysis.py — but
        # NOT silently: each degradation is named once on stderr.
        if hasattr(_analysis, "serve_structure_errors"):
            errors.extend(_analysis.serve_structure_errors(segment))
        else:
            _note_degraded("analysis.py predates serve_structure_errors",
                           _SERVE_SKIP)
        # the program-cost record contract (telemetry/costs.py harvest
        # points) — same file-load sharing, same named degrade
        if hasattr(_analysis, "cost_record_errors"):
            errors.extend(_analysis.cost_record_errors(segment))
        else:
            _note_degraded("analysis.py predates cost_record_errors",
                           _COST_SKIP)
        # the dispatch-forensics record contract (telemetry/dispatch.py
        # epoch flushes, read by `trace report --overhead`) — same
        # file-load sharing, same named degrade
        if hasattr(_analysis, "dispatch_record_errors"):
            errors.extend(_analysis.dispatch_record_errors(segment))
        else:
            _note_degraded("analysis.py predates dispatch_record_errors",
                           _DISPATCH_SKIP)
        # the performance-ledger record contract (telemetry/ledger.py
        # rows re-emitted by `ledger --telemetry`, cli/ledger.py) — same
        # file-load sharing, same named degrade
        if hasattr(_analysis, "ledger_row_errors"):
            errors.extend(_analysis.ledger_row_errors(segment))
        else:
            _note_degraded("analysis.py predates ledger_row_errors",
                           _LEDGER_SKIP)
        # the fleet/reload record contract (serve/fleet.py transitions,
        # serve/reload.py verdicts — including the drain-before-swap
        # invariant outstanding_at_swap == 0) — same file-load sharing,
        # same named degrade
        if hasattr(_analysis, "fleet_record_errors"):
            errors.extend(_analysis.fleet_record_errors(segment))
        else:
            _note_degraded("analysis.py predates fleet_record_errors",
                           _FLEET_SKIP)
        errors.sort(key=lambda e: e[0])
        return errors
    _note_degraded("analysis.py not found beside this script (span "
                   "structure degrades to orphaned-parent detection)",
                   _SERVE_SKIP)
    _note_degraded("analysis.py not found beside this script", _COST_SKIP)
    _note_degraded("analysis.py not found beside this script",
                   _DISPATCH_SKIP)
    _note_degraded("analysis.py not found beside this script",
                   _LEDGER_SKIP)
    _note_degraded("analysis.py not found beside this script",
                   _FLEET_SKIP)
    return _fallback_structure_errors(segment)


def check_file(path: str, errors: list) -> int:
    """Validate one JSONL file; appends "path:line: why" strings to
    `errors` and returns the number of records read.

    The writer opens in APPEND mode (crash/outage-resume friendly), so one
    file may hold several run segments, each beginning with a
    `trace_start` meta record. Ordering and span-id scope reset per
    segment: t_mono is monotonic within a segment (perf_counter restarts
    across processes/reboots), and span structure — parent resolution, id
    uniqueness, enter/exit stamps, nesting containment — is validated per
    segment by the reconstructor shared with telemetry/analysis.py."""
    segment = []  # this segment's span records, for the tree reconstructor
    last_mono = None
    n = 0

    def flush_segment():
        errors.extend(f"{path}:{line}: {msg}"
                      for line, msg in span_structure_errors(segment))
        segment.clear()

    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            where = f"{path}:{line_no}"
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                rec = json.loads(line)
            except ValueError as e:
                errors.append(f"{where}: malformed JSON ({e})")
                continue
            if not isinstance(rec, dict):
                errors.append(f"{where}: record is not an object")
                continue
            missing = [k for k in REQUIRED if k not in rec]
            if missing:
                errors.append(f"{where}: missing fields {missing}")
                continue
            if rec["v"] != SCHEMA_VERSION:
                errors.append(f"{where}: unknown schema version {rec['v']!r}")
                continue
            if rec["kind"] not in KINDS:
                errors.append(f"{where}: unknown kind {rec['kind']!r}")
                continue
            if rec["kind"] == "meta" and rec["name"] == "trace_start":
                flush_segment()     # a new appended run: fresh id scope
                last_mono = None    # and a fresh monotonic clock
            if not isinstance(rec["t_mono"], (int, float)):
                errors.append(f"{where}: t_mono is not a number")
                continue
            if last_mono is not None and rec["t_mono"] < last_mono:
                errors.append(f"{where}: out of order (t_mono "
                              f"{rec['t_mono']} < previous {last_mono})")
            last_mono = rec["t_mono"]
            if rec["kind"] == "point" and rec["name"] == "health":
                attrs = rec.get("attrs") or {}
                missing_h = [k for k in HEALTH_REQUIRED if k not in attrs]
                if missing_h:
                    errors.append(f"{where}: health event missing attrs "
                                  f"{missing_h}")
                else:
                    if not (isinstance(attrs["detector"], str)
                            and attrs["detector"]):
                        errors.append(f"{where}: health detector must be a "
                                      f"non-empty string; got "
                                      f"{attrs['detector']!r}")
                    if attrs["severity"] not in HEALTH_SEVERITIES:
                        errors.append(f"{where}: unknown health severity "
                                      f"{attrs['severity']!r}; known: "
                                      f"{HEALTH_SEVERITIES}")
            if rec["kind"] == "point" and rec["name"] in (
                    "program_cost", "dispatch_phase", "dispatch_window",
                    "ledger_row", "fleet_event", "reload_event"):
                # cost, dispatch, and ledger records ride the segment so
                # the shared validators (analysis.cost_record_errors /
                # dispatch_record_errors / ledger_row_errors) see them;
                # the span-tree checks skip non-span kinds by construction
                rec["_line"] = line_no
                segment.append(rec)
            if rec["kind"] == "span":
                for k in ("span", "dur_s"):
                    if k not in rec:
                        errors.append(f"{where}: span record missing {k!r}")
                        break
                else:
                    if not isinstance(rec["dur_s"], (int, float)):
                        errors.append(f"{where}: dur_s is not a number")
                    elif rec["dur_s"] < 0:
                        errors.append(f"{where}: negative dur_s "
                                      f"{rec['dur_s']}")
                    rec["_line"] = line_no
                    segment.append(rec)
    flush_segment()
    return n


def check_flight_dump(path: str, errors: list) -> int:
    """Validate a flight-recorder dump (`flight.<pid>.json`, dumped beside
    the trace by --telemetry runs) — the merged-dump attribution contract:
    a v2+ dump's entries each carry an int `rank` stamped at record time
    (telemetry/flight.py), so a merged multi-rank post-mortem is
    attributable. v1 dumps predate the field and are exempt (backward
    compatibility is the dump READER's contract; the checker enforces only
    what the writer of that schema version promised). Returns the entry
    count."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except ValueError as e:
        errors.append(f"{path}: malformed flight dump JSON ({e})")
        return 0
    if not isinstance(payload, dict) or not isinstance(
            payload.get("entries"), list):
        errors.append(f"{path}: flight dump is not an object with an "
                      f"'entries' list")
        return 0
    v = payload.get("v")
    for i, e in enumerate(payload["entries"]):
        if not isinstance(e, dict):
            errors.append(f"{path}: entry {i} is not an object")
            continue
        if isinstance(v, int) and v >= 2:
            r = e.get("rank")
            if not isinstance(r, int) or isinstance(r, bool):
                errors.append(f"{path}: entry {i} "
                              f"({e.get('kind', '?')}) missing the int "
                              f"rank field a v{v} dump promises")
    return len(payload["entries"])


def _snapshot_metric_names(path: str) -> set:
    """All metric names appearing in a file's registry-snapshot records
    (counters + gauges + histograms). Tolerant of malformed lines — the
    schema pass already reported those."""
    names: set = set()
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or rec.get("kind") != "snapshot":
                continue
            attrs = rec.get("attrs") or {}
            for table in ("counters", "gauges", "histograms"):
                t = attrs.get(table)
                if isinstance(t, dict):
                    names.update(t)
    return names


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # --require PREFIX (repeatable): fail unless the trace's registry
    # snapshot holds at least one metric whose name starts with PREFIX —
    # e.g. `--require ddp.` in `make ddp-smoke` fails on any run that
    # silently dropped the DDP comms metrics. Parsed by hand so the
    # historical exit codes (2 = usage) stay exactly pinned by tests.
    require = []
    while "--require" in argv:
        i = argv.index("--require")
        if i + 1 >= len(argv):
            print(__doc__.strip(), file=sys.stderr)
            return 2
        # one value may carry several comma-separated prefixes (gate N
        # metric families in one invocation); empty segments — a trailing
        # comma — are usage errors, not silently-satisfied gates
        prefixes = [p.strip() for p in argv[i + 1].split(",")]
        if not all(prefixes):
            print(f"check_telemetry: --require {argv[i + 1]!r} contains "
                  f"an empty prefix", file=sys.stderr)
            return 2
        require.extend(prefixes)
        del argv[i:i + 2]
    if len(argv) != 1:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    target = argv[0]
    if os.path.isdir(target):
        files = sorted(glob.glob(os.path.join(target, "events*.jsonl")))
        if not files:
            print(f"check_telemetry: no events*.jsonl under {target}",
                  file=sys.stderr)
            return 1
    elif os.path.exists(target):
        files = [target]
    else:
        print(f"check_telemetry: {target} does not exist", file=sys.stderr)
        return 1
    errors: "list[str]" = []
    total = 0
    for path in files:
        got = check_file(path, errors)
        if got == 0:
            errors.append(f"{path}: empty trace")
        total += got
    if os.path.isdir(target):
        # flight dumps landing beside the trace (set_dump_dir wires
        # --telemetry DIR): validate the rank-attribution contract
        for path in sorted(glob.glob(os.path.join(target,
                                                  "flight.*.json"))):
            total += check_flight_dump(path, errors)
    if require:
        names: set = set()
        for path in files:
            names.update(_snapshot_metric_names(path))
        for prefix in require:
            if not any(n.startswith(prefix) for n in names):
                errors.append(
                    f"{target}: no registry-snapshot metric matching "
                    f"--require {prefix!r} (snapshot metrics: "
                    f"{sorted(names) or 'none'})")
    if errors:
        for e in errors:
            print(f"check_telemetry: {e}", file=sys.stderr)
        print(f"check_telemetry: FAIL — {len(errors)} violation(s) across "
              f"{len(files)} file(s)", file=sys.stderr)
        return 1
    print(f"check_telemetry: OK — {total} record(s) across {len(files)} "
          f"file(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
