#!/usr/bin/env python
"""Performance-ledger smoke: prove the committed-artifact history ingests,
reports, self-gates clean, catches an injected regression BY NAME, and
renders as Perfetto counter tracks.

    python scripts/ledger_smoke.py [--workdir DIR]

The front door of docs/OBSERVABILITY.md §Performance ledger
(`make ledger-smoke`). Five legs, all over the artifacts actually
committed in-repo (zero hand-edits to them):

  1. REPORT  — `ledger report --json` must cover the six acceptance
     metric families (images/sec, scaling efficiency, peak HBM, serve
     p50/p99, data-wait share, overhead share) and the markdown
     rendering must table every series.
  2. SELF-GATE — `ledger gate . --telemetry DIR` exits 0 on the real
     trajectory, and `check_telemetry --require ledger.` validates the
     emitted ledger_row records + registry census.
  3. REGRESSION — a scratch copy of the history plus an injected
     MULTICHIP_r09 (ok bit dropped, throughput halved) must exit 3
     NAMING the regressed series and the offending run/source; the
     pairwise CLI's `trace report ... --ledger DIR` multi-run mode must
     agree.
  4. REFUSAL  — an artifact stamped with a FUTURE schema_version must be
     refused by name, never silently dropped.
  5. PERFETTO — `trace export --ledger` must render one counter track
     per series on the ledger pid, one point per run.

Exit codes: 0 = every leg held; 1 = any leg failed.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The acceptance floor (ISSUE 18): one representative metric per family
# the report must cover from the committed artifacts alone.
REQUIRED_METRICS = (
    "bench.train_images_per_sec_per_chip",   # images/sec
    "ddp.scaling_efficiency_vs_1dev",        # scaling efficiency
    "cost.peak_hbm_bytes",                   # peak HBM
    "serve.p50_ms",                          # serve p50
    "serve.p99_ms",                          # serve p99
    "input.data_wait_share_p95",             # data-wait share
    "ddp.overhead_share",                    # overhead share
)


def _run(argv, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(kw.pop("extra_env", {}))
    return subprocess.run([sys.executable] + argv, cwd=REPO, env=env,
                          capture_output=True, text=True, **kw)


def _fail(leg: str, why: str, proc=None) -> int:
    print(f"ledger_smoke: FAIL [{leg}] {why}", file=sys.stderr)
    if proc is not None:
        sys.stderr.write(proc.stdout[-2000:])
        sys.stderr.write(proc.stderr[-2000:])
    return 1


def leg_report() -> int:
    p = _run(["-m", "pytorch_ddp_mnist_tpu", "ledger", "report", ".",
              "--json"])
    if p.returncode != 0:
        return _fail("report", f"exit {p.returncode}", p)
    rep = json.loads(p.stdout)
    metrics = {s["metric"] for s in rep["series"]}
    missing = [m for m in REQUIRED_METRICS if m not in metrics]
    if missing:
        return _fail("report", f"acceptance metrics missing from the "
                               f"committed history: {missing}")
    if len(rep["families"]) < 6:
        return _fail("report", f"only {len(rep['families'])} metric "
                               f"families ({rep['families']}); need >= 6")
    md = _run(["-m", "pytorch_ddp_mnist_tpu", "ledger", "report", "."])
    if md.returncode != 0:
        return _fail("report", f"markdown exit {md.returncode}", md)
    table_rows = [ln for ln in md.stdout.splitlines()
                  if ln.startswith("| ") and not ln.startswith("| series")]
    if len(table_rows) != rep["n_series"]:
        return _fail("report", f"markdown tables {len(table_rows)} rows "
                               f"for {rep['n_series']} series")
    print(f"ledger_smoke: report OK — {rep['n_series']} series, "
          f"{rep['n_rows']} rows, families {rep['families']}")
    return 0


def leg_self_gate(workdir: str) -> int:
    tdir = os.path.join(workdir, "telemetry")
    p = _run(["-m", "pytorch_ddp_mnist_tpu", "ledger", "gate", ".",
              "--telemetry", tdir])
    if p.returncode != 0:
        return _fail("self-gate", f"the committed trajectory must gate "
                                  f"clean; exit {p.returncode}", p)
    c = _run(["scripts/check_telemetry.py", "--require", "ledger.", tdir])
    if c.returncode != 0:
        return _fail("self-gate", f"check_telemetry --require ledger. "
                                  f"exit {c.returncode}", c)
    print("ledger_smoke: self-gate OK — exit 0 + ledger_row records "
          "validated")
    return 0


def _build_fixture(workdir: str) -> str:
    """A scratch history: every committed artifact, plus an injected
    MULTICHIP_r09 whose ok bit dropped and whose throughput rows halved —
    a direction-aware regression on several series at once."""
    fixture = os.path.join(workdir, "fixture")
    os.makedirs(fixture, exist_ok=True)
    sys.path.insert(0, REPO)
    from pytorch_ddp_mnist_tpu.telemetry.ledger import discover
    for path in discover(REPO):
        shutil.copy(path, fixture)
    with open(os.path.join(REPO, "MULTICHIP_r08.json")) as f:
        bad = json.load(f)
    bad["ok"] = False
    bad["rc"] = 1
    bad["schema_version"] = 2
    bad["run_ord"] = 9
    for row in bad.get("strategies") or []:
        for field in ("images_per_sec", "per_chip_images_per_sec",
                      "scaling_efficiency_vs_1dev"):
            if isinstance(row.get(field), (int, float)):
                row[field] = row[field] / 2.0
    with open(os.path.join(fixture, "MULTICHIP_r09.json"), "w") as f:
        json.dump(bad, f, indent=2)
    return fixture


def leg_regression(workdir: str) -> int:
    fixture = _build_fixture(workdir)
    p = _run(["-m", "pytorch_ddp_mnist_tpu", "ledger", "gate", fixture])
    if p.returncode != 3:
        return _fail("regression", f"injected regression must exit 3; "
                                   f"got {p.returncode}", p)
    for needle in ("multichip.ok", "ddp.images_per_sec",
                   "MULTICHIP_r09.json"):
        if needle not in p.stderr:
            return _fail("regression", f"exit-3 output must name "
                                       f"{needle!r}", p)
    # the pairwise CLI's multi-run mode must reach the same verdict
    target = os.path.join(fixture, "MULTICHIP_r09.json")
    t = _run(["-m", "pytorch_ddp_mnist_tpu", "trace", "report", target,
              "--ledger", fixture])
    if t.returncode != 3:
        return _fail("regression", f"trace report --ledger must exit 3; "
                                   f"got {t.returncode}", t)
    if "MULTICHIP_r09.json" not in t.stderr:
        return _fail("regression", "trace report --ledger exit-3 output "
                                   "must name the offending artifact", t)
    print("ledger_smoke: regression OK — exit 3 naming series + run, "
          "both front doors")
    return 0


def leg_refusal(workdir: str) -> int:
    alien = os.path.join(workdir, "alien")
    os.makedirs(alien, exist_ok=True)
    with open(os.path.join(alien, "BENCH_r99.json"), "w") as f:
        json.dump({"schema_version": 99, "metric": "x", "value": 1.0}, f)
    p = _run(["-m", "pytorch_ddp_mnist_tpu", "ledger", "gate", alien])
    if p.returncode != 1:
        return _fail("refusal", f"future schema_version must exit 1; got "
                                f"{p.returncode}", p)
    if "BENCH_r99.json" not in p.stderr or "schema_version 99" \
            not in p.stderr:
        return _fail("refusal", "refusal must name the file and the "
                                "unknown version", p)
    print("ledger_smoke: refusal OK — future schema_version refused by "
          "name")
    return 0


def leg_perfetto(workdir: str) -> int:
    out = os.path.join(workdir, "ledger.chrome.json")
    p = _run(["-m", "pytorch_ddp_mnist_tpu", "trace", "export",
              os.path.join(workdir, "noevents"), "--ledger", ".",
              "-o", out])
    if p.returncode != 0:
        return _fail("perfetto", f"exit {p.returncode}", p)
    with open(out) as f:
        trace = json.load(f)
    counters = [e for e in trace["traceEvents"]
                if e.get("ph") == "C" and e.get("cat") == "ledger"]
    if not counters:
        return _fail("perfetto", "no ledger counter events in the export")
    rep = json.loads(_run(["-m", "pytorch_ddp_mnist_tpu", "ledger",
                           "report", ".", "--json"]).stdout)
    if len(counters) != rep["n_rows"]:
        return _fail("perfetto", f"{len(counters)} counter points for "
                                 f"{rep['n_rows']} ledger rows")
    multi = [s for s in rep["series"] if s["n"] > 1]
    for s in multi:
        pts = [e for e in counters if e["name"] == s["series"]]
        if len(pts) != s["n"] or len({e["ts"] for e in pts}) != s["n"]:
            return _fail("perfetto", f"series {s['series']} must render "
                                     f"{s['n']} distinct-ts points")
    print(f"ledger_smoke: perfetto OK — {len(counters)} counter points "
          f"across {rep['n_series']} series "
          f"({len(multi)} multi-run series scrubbable)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workdir", default="/tmp/pdmt_ledger_smoke",
                    help="scratch dir (default %(default)s; wiped)")
    a = ap.parse_args(argv)
    shutil.rmtree(a.workdir, ignore_errors=True)
    os.makedirs(a.workdir, exist_ok=True)
    for leg in (leg_report,
                lambda: leg_self_gate(a.workdir),
                lambda: leg_regression(a.workdir),
                lambda: leg_refusal(a.workdir),
                lambda: leg_perfetto(a.workdir)):
        rc = leg()
        if rc:
            return rc
    print("ledger_smoke: OK — all five legs held")
    return 0


if __name__ == "__main__":
    sys.exit(main())
