#!/usr/bin/env python
"""Serve fast-path before/after artifact (SERVE_r0X.json — docs/PERF.md
§Serving path).

Runs `bench.py --mode serve` over an offered-rps grid, N trials per
point, for BOTH flush paths — the staged fast path and `--no_fast` (the
legacy stack-at-flush path, i.e. the pre-ISSUE-14 engine) — at one fixed
loadgen geometry, and reduces each point to per-trial medians. The
headline each artifact commits: **max sustained QPS at the fixed p99
SLO** (a point "sustains" when its median p99 is within the SLO and its
median reject rate is under the cap), per path, plus the per-stage
share table at each path's saturation point.

One bench subprocess per trial: every measurement gets a fresh engine,
registry, and reply thread — trials cannot warm each other.

    JAX_PLATFORMS=cpu python scripts/serve_fast_bench.py -o SERVE_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(offered_rps: float, a, fast: bool) -> dict:
    cmd = [sys.executable, os.path.join(ROOT, "bench.py"), "--mode",
           "serve", "--requests", str(a.requests), "--offered_rps",
           str(offered_rps), "--max_batch", str(a.max_batch),
           "--max_delay_ms", str(a.max_delay_ms),
           "--queue_depth", str(a.queue_depth)]
    if not fast:
        cmd.append("--no_fast")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         timeout=600)
    if out.returncode != 0:
        raise RuntimeError(f"bench failed ({' '.join(cmd)}):\n"
                           f"{out.stderr[-2000:]}")
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def _reduce_point(rps: float, trials, a) -> dict:
    med = {k: round(statistics.median(tr[k] for tr in trials), 3)
           for k in ("value", "p50_ms", "p99_ms", "reject_rate",
                     "batch_occupancy")}
    sustained = (med["p99_ms"] <= a.slo_p99_ms
                 and med["reject_rate"] <= a.reject_cap)
    # the stage table of the median-achieved trial (one honest run's
    # decomposition, not an average of averages)
    rep = sorted(trials, key=lambda tr: tr["value"])[len(trials) // 2]
    return {"offered_rps": rps, "trials": len(trials), **med,
            "sustained": sustained,
            "stage_attribution": rep.get("stage_attribution"),
            "staging_grown": rep.get("staging_grown")}


def _reduce_path(label: str, points) -> dict:
    sustained = [p for p in points if p["sustained"]]
    best = max(sustained, key=lambda p: p["value"]) if sustained else None
    return {
        "path": label,
        "points": points,
        "max_sustained_qps": best["value"] if best else None,
        "at_offered_rps": best["offered_rps"] if best else None,
        "p99_ms_at_max": best["p99_ms"] if best else None,
        "stages_at_max": best["stage_attribution"] if best else None,
    }


def sweep(a):
    """Both paths, INTERLEAVED trial by trial (legacy, fast, legacy,
    fast, ...) at every grid point: this host's ambient load drifts on
    the scale of a whole sweep, so back-to-back pairing is the only fair
    comparison — a path never gets a quieter machine than its rival."""
    before_pts, after_pts = [], []
    for rps in a.grid:
        trials = {"legacy": [], "fast": []}
        for t in range(a.trials):
            for fast, label in ((False, "legacy"), (True, "fast")):
                rec = run_bench(rps, a, fast)
                trials[label].append(rec)
                print(f"  {label} offered={rps:.0f} trial {t + 1}: "
                      f"ach={rec['value']:.0f} p99={rec['p99_ms']:.2f}ms "
                      f"rej={rec['reject_rate']:.3f}", file=sys.stderr,
                      flush=True)
        before_pts.append(_reduce_point(rps, trials["legacy"], a))
        after_pts.append(_reduce_point(rps, trials["fast"], a))
    return (_reduce_path("legacy", before_pts),
            _reduce_path("fast", after_pts))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-o", "--out", default=None,
                   help="write the artifact JSON here (stdout always)")
    p.add_argument("--grid", type=float, nargs="+",
                   default=[16000.0, 20000.0, 24000.0, 28000.0],
                   help="offered-rps grid (default spans this host's "
                        "saturation knee — the committed SERVE_r01 "
                        "geometry)")
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--requests", type=int, default=3600)
    p.add_argument("--max_batch", type=int, default=32)
    p.add_argument("--max_delay_ms", type=float, default=2.0)
    p.add_argument("--queue_depth", type=int, default=256)
    p.add_argument("--slo_p99_ms", type=float, default=25.0,
                   help="the fixed p99 SLO a point must hold to count as "
                        "sustained")
    p.add_argument("--reject_cap", type=float, default=0.01,
                   help="max median reject rate for a sustained point")
    a = p.parse_args(argv)
    if a.trials < 1 or a.requests < 1:
        p.error("--trials/--requests must be >= 1")

    t0 = time.time()
    artifact = {
        "artifact": "serve_fast_path_before_after",
        "v": 1,
        "geometry": {"requests": a.requests, "max_batch": a.max_batch,
                     "max_delay_ms": a.max_delay_ms,
                     "queue_depth": a.queue_depth,
                     "grid_offered_rps": a.grid, "trials": a.trials,
                     "slo_p99_ms": a.slo_p99_ms,
                     "reject_cap": a.reject_cap},
        "host": {"cpus": os.cpu_count(), "platform": "cpu"},
    }
    # `legacy` is the pre-fast-path flush (`--no_fast`: stack rows at
    # flush, fetch synchronously ON the event loop) — the before side;
    # `fast` is the staged path (persistent staging, zero-copy forming,
    # double-buffered H2D, adaptive off-loop reply). Trials interleave.
    artifact["before"], artifact["after"] = sweep(a)
    b, f = artifact["before"], artifact["after"]
    if b["max_sustained_qps"] and f["max_sustained_qps"]:
        artifact["qps_gain"] = round(
            f["max_sustained_qps"] / b["max_sustained_qps"], 4)
    artifact["wall_s"] = round(time.time() - t0, 1)
    blob = json.dumps(artifact, indent=2)
    print(blob)
    if a.out:
        with open(a.out, "w") as fh:
            fh.write(blob + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
