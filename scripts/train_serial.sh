#!/bin/bash
# Serial baseline trainer — the reference train_cpu.sh analog
# (/root/reference/train_cpu.sh:3 runs ddp_tutorial_cpu.py, 1 epoch).
set -e
cd "$(dirname "$0")/.."
python -m pytorch_ddp_mnist_tpu.cli.train --n_epochs 1 "$@"
