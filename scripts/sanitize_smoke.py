#!/usr/bin/env python
"""Sanitize smoke (docs/STATIC_ANALYSIS.md §Runtime sanitizers): drive the
REAL serve request path and a short REAL training run with all three
runtime sanitizers armed, and fail loudly if any runtime contract breaks.

    JAX_PLATFORMS=cpu python scripts/sanitize_smoke.py     # = make sanitize-smoke

What each phase pins:

  * serve selftest (open-loop loadgen through admission -> micro-batcher
    -> bucketed AOT engine, telemetry DISABLED):
      - `no_host_sync`: zero block_until_ready calls, and EXACTLY two
        device->host fetches (logits + preds) per batcher flush — the
        NullTracer zero-overhead contract from tests/test_serve_trace.py,
        now checked against the live request path;
      - `event_loop_stall`: no single event-loop callback (coroutine step
        or timer) runs longer than the threshold — the PR 9
        sort-per-offered-request bug class as a harness ($PDMT_STALL_MS,
        default 250: generous enough for an honest CPU engine flush,
        far below any sleep/sort/IO stall worth catching).
  * 2-epoch training run (synthetic MNIST, the tests' tiny-fit shape):
      - `no_host_sync`: zero block_until_ready, and fetches bounded
        EPOCH-granularly (<= 6 per epoch: loss curve, health aux, eval —
        the tests/test_health.py budget), never per step.
  * both phases run inside one `lock_trace`: every lock created during
    the run records its acquisition order, and any observed order cycle
    (LOCK002's runtime confirmation) fails the smoke.

Prints one JSON line on success; exit 1 with the sanitizer's message on
violation. Pure CPU, seconds of wall time — wired into `make check`.
"""

from __future__ import annotations

import json
import os
import sys

# runnable from anywhere: the repo root (this script's parent's parent)
# fronts sys.path so the package imports without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _serve_phase(sanitize, stall_ms: float) -> dict:
    import jax

    from pytorch_ddp_mnist_tpu import telemetry
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.serve import InferenceEngine, ServeService
    from pytorch_ddp_mnist_tpu.serve.loadgen import request_rows, run_loadgen

    engine = InferenceEngine(init_mlp(jax.random.key(0)), max_batch=32)
    engine.predict(request_rows(1, seed=7))   # warm the host path pre-arm
    service = ServeService(engine, max_delay_ms=2.0, max_depth=256,
                           registry=telemetry.MetricsRegistry())
    if not service.batcher.fast_path:
        raise sanitize.SanitizerError(
            "serve selftest is not on the staged fast path — the smoke "
            "must pin the path production actually runs")
    with sanitize.no_host_sync() as sync, \
            sanitize.event_loop_stall(threshold_ms=stall_ms) as loop_guard:
        out = run_loadgen(service, offered_rps=1500.0, n_requests=200,
                          seed=0)
    flushes = service.batcher.flushes
    if out["completed"] != 200:
        raise sanitize.SanitizerError(
            f"serve selftest completed {out['completed']}/200 requests")
    if sync.fetches != 2 * flushes:
        raise sanitize.HostSyncError(
            f"serve path made {sync.fetches} device fetches across "
            f"{flushes} flushes; the contract is exactly 2 (logits + "
            f"preds) per flush — now fetched on the REPLY thread, where "
            f"the interception still counts them")
    return {"completed": out["completed"], "flushes": flushes,
            "fetches": sync.fetches,
            "block_until_ready": sync.block_until_ready_calls,
            "stalls": len(loop_guard.stalls),
            # the fast-path invariants ride the smoke line: the staged
            # path served, and the staging pool never grew past its
            # double buffer (zero host allocations per flush)
            "fast_path": service.batcher.fast_path,
            "staging_grown": engine.staging_grown}


def _train_phase(sanitize) -> dict:
    import numpy as np
    import jax

    from pytorch_ddp_mnist_tpu.data import (BatchLoader, normalize_images,
                                            synthetic_mnist)
    from pytorch_ddp_mnist_tpu.models import init_mlp
    from pytorch_ddp_mnist_tpu.parallel import ShardedSampler
    from pytorch_ddp_mnist_tpu.train import TrainState, fit

    epochs = 2
    train = synthetic_mnist(128, seed=0)
    test = synthetic_mnist(64, seed=1)
    sampler = ShardedSampler(128, num_replicas=1, rank=0, seed=42)
    loader = BatchLoader(normalize_images(train.images), train.labels,
                         sampler, batch_size=32)
    state = TrainState(init_mlp(jax.random.key(0)), jax.random.key(1))
    with sanitize.no_host_sync(max_fetches=epochs * 6) as sync:
        fit(state, loader, normalize_images(test.images),
            test.labels.astype(np.int32), epochs=epochs, batch_size=32,
            lr=0.1, log=lambda _m: None)
    return {"epochs": epochs, "fetches": sync.fetches,
            "block_until_ready": sync.block_until_ready_calls}


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    stall_ms = float(os.environ.get("PDMT_STALL_MS", "250"))
    from pytorch_ddp_mnist_tpu.statics import sanitize

    out = {"stall_threshold_ms": stall_ms}
    try:
        with sanitize.lock_trace() as locks:
            out["serve"] = _serve_phase(sanitize, stall_ms)
            out["train"] = _train_phase(sanitize)
        out["lock_edges"] = len(locks.edges())
        out["lock_cycles"] = 0
    except sanitize.SanitizerError as e:
        print(f"sanitize_smoke: FAIL — {e}", file=sys.stderr)
        return 1
    out["ok"] = True
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
