# Developer entry points. The framework has no build step; `native` compiles
# the optional C++ reader core (ctypes loads it on demand otherwise).
PY ?= python

.PHONY: test test-fast test-integration bench serve-smoke serve-trace-smoke serve-fast-smoke obs-smoke trace-smoke ddp-smoke chaos-smoke serve-chaos-smoke cluster-smoke elastic-smoke health-smoke lint audit-program static-smoke sanitize-smoke input-smoke cost-smoke overhead-smoke ledger-smoke check native clean convert

# BOTH tiers — the committed way to run everything (-m "" overrides the
# fast-tier default addopts in pyproject.toml).
test:
	$(PY) -m pytest tests/ -m "" -q

test-fast:
	$(PY) -m pytest tests/ -q

test-integration:
	$(PY) -m pytest tests/ -m integration -q

bench:
	$(PY) bench.py

# Full serve request path (admission -> micro-batcher -> bucketed AOT
# engine) end-to-end on the host backend: one JSON line or a nonzero exit.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --mode serve --requests 300 --offered_rps 1500

# Request-tracing round trip (docs/OBSERVABILITY.md §Request tracing): a
# loadgen burst against a live traced engine, then the emitted
# request/batch spans are schema- AND contract-validated (non-empty
# request_id, batch links resolving, pipeline-ordered stages, serve.*
# registry metrics present), the tail-latency attribution report renders
# (per-stage p50/p95/p99, %-of-e2e, slowest-request trees), and the
# Perfetto export with request/batch flow arrows is checked non-empty.
serve-trace-smoke:
	rm -rf /tmp/pdmt_serve_trace
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu serve \
		--selftest 300 --offered_rps 1500 \
		--telemetry /tmp/pdmt_serve_trace
	$(PY) scripts/check_telemetry.py --require serve. /tmp/pdmt_serve_trace
	$(PY) -m pytorch_ddp_mnist_tpu trace report --serve /tmp/pdmt_serve_trace
	$(PY) -m pytorch_ddp_mnist_tpu trace export /tmp/pdmt_serve_trace \
		-o /tmp/pdmt_serve_trace/trace.chrome.json
	$(PY) -c "import json; \
		d = json.load(open('/tmp/pdmt_serve_trace/trace.chrome.json')); \
		assert any(e.get('ph') == 's' for e in d['traceEvents']), \
		'no request->batch flow arrows in chrome trace'"

# Fast-path smoke (docs/SERVING.md §Fast path): a loadgen burst through
# the staged fast path (persistent staging + off-loop reply) with
# request tracing on, then the serve.* registry surface is checked, and
# the run is gated against ITSELF through the stage-share regression
# gate (`trace report --serve --baseline`) — proving the gate's full
# plumbing fires on every `make check` (a run never regresses against
# itself; a broken gate or a missing stage exits nonzero here).
serve-fast-smoke:
	rm -rf /tmp/pdmt_serve_fast
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu serve \
		--selftest 400 --offered_rps 3000 --max_batch 32 \
		--telemetry /tmp/pdmt_serve_fast
	$(PY) scripts/check_telemetry.py --require serve. /tmp/pdmt_serve_fast
	$(PY) -m pytorch_ddp_mnist_tpu trace report --serve --json \
		/tmp/pdmt_serve_fast > /tmp/pdmt_serve_fast/self.json
	$(PY) -m pytorch_ddp_mnist_tpu trace report --serve \
		/tmp/pdmt_serve_fast --baseline /tmp/pdmt_serve_fast/self.json

# Observability smoke: 1 CPU epoch with --telemetry, then schema-validate
# the emitted JSONL trace (nonzero exit on malformed/unordered records).
obs-smoke:
	rm -rf /tmp/pdmt_obs_smoke
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu train --epochs 1 \
		--limit 512 --batch_size 64 --checkpoint "" \
		--telemetry /tmp/pdmt_obs_smoke
	$(PY) scripts/check_telemetry.py /tmp/pdmt_obs_smoke

# Trace-analysis round trip: emit a real trace (1 CPU epoch), validate the
# schema + span structure, render the phase report, self-gate it against
# its own baseline (a run never regresses against itself), and export the
# Perfetto-loadable Chrome trace. Nonzero exit on any failure.
trace-smoke:
	rm -rf /tmp/pdmt_trace_smoke
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu train --epochs 2 \
		--limit 512 --batch_size 64 --checkpoint "" \
		--telemetry /tmp/pdmt_trace_smoke
	$(PY) scripts/check_telemetry.py /tmp/pdmt_trace_smoke
	$(PY) -m pytorch_ddp_mnist_tpu trace report /tmp/pdmt_trace_smoke
	$(PY) -m pytorch_ddp_mnist_tpu trace report /tmp/pdmt_trace_smoke \
		--baseline /tmp/pdmt_trace_smoke
	$(PY) -m pytorch_ddp_mnist_tpu trace export /tmp/pdmt_trace_smoke \
		-o /tmp/pdmt_trace_smoke/trace.chrome.json
	$(PY) -c "import json; \
		d = json.load(open('/tmp/pdmt_trace_smoke/trace.chrome.json')); \
		assert d['traceEvents'], 'empty chrome trace'"

# DDP comms smoke: the FULL strategy matrix (pmean/sharded/bf16/int8,
# each with and without --overlap bucket-pipelining) on an 8-fake-device
# CPU mesh — one telemetry-instrumented --parallel epoch per combination,
# each trace schema-validated AND gated on the ddp.* metrics being present
# (a run that silently dropped ddp.bytes_on_wire / ddp.collective_s
# fails), then `bench.py --mode ddp` emits the per-strategy artifact
# lines (throughput + scaling efficiency + parity drift vs pmean) at a
# model scale where the strategies actually separate (--param_scale 2
# keeps the smoke quick; the committed MULTICHIP artifact measures 16).
ddp-smoke:
	rm -rf /tmp/pdmt_ddp_smoke
	for comm in pmean sharded bf16 int8; do \
		for ov in "" "--overlap"; do \
			name=$$comm$${ov:+_overlap}; \
			JAX_PLATFORMS=cpu \
			XLA_FLAGS=--xla_force_host_platform_device_count=8 \
			$(PY) -m pytorch_ddp_mnist_tpu train --parallel \
				--wireup_method single --ddp_comm $$comm $$ov \
				--epochs 1 --limit 512 --batch_size 16 \
				--checkpoint "" \
				--telemetry /tmp/pdmt_ddp_smoke/$$name || exit 1; \
			$(PY) scripts/check_telemetry.py --require ddp. \
				/tmp/pdmt_ddp_smoke/$$name || exit 1; \
		done; \
	done
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) bench.py --mode ddp --epochs 3 --batch_size 16 \
			--param_scale 2

# Cluster-forensics smoke (docs/OBSERVABILITY.md §Cluster forensics):
# a 2-process journaled world trains clean (per-rank collective journals
# agree, `check_telemetry --require cluster.,ddp.` gates BOTH metric
# families in one invocation, the Perfetto export carries per-rank
# collective tracks + cross-rank seq flow arrows); then an injected
# `collective_timeout` on rank 0 must produce a `trace report --cluster`
# hang report naming the stuck seq/kind and every rank's last journal
# position; then a synthetic desynced journal pair must exit 3 naming
# both ranks. On a jaxlib without CPU multiprocess collectives it
# degrades to the same matrix at world=1 (script exit 75 = the
# multiproc-skip signal, the chaos-smoke convention).
cluster-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/cluster_smoke.py || \
		{ rc=$$?; [ $$rc -eq 75 ] && \
		JAX_PLATFORMS=cpu $(PY) scripts/cluster_smoke.py --world 1; }

# Chaos smoke (docs/ROBUSTNESS.md): SIGKILL a seeded rank of a 4-process
# fake-CPU-device training run at a seeded mid-epoch step, relaunch with
# --resume <step-ckpt dir>, assert the finished params are BYTE-identical
# to the unbroken baseline, and gate the resumed run's telemetry on the
# checkpoint.* metrics (check_telemetry --require checkpoint.). On a
# jaxlib without CPU multiprocess collectives it degrades to the same
# matrix at world=1 (script exit 75 is the multiproc skip signal).
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/chaos_smoke.py || \
		{ rc=$$?; [ $$rc -eq 75 ] && \
		JAX_PLATFORMS=cpu $(PY) scripts/chaos_smoke.py --world 1; }

# Serving chaos (docs/ROBUSTNESS.md §Serving chaos): a 2-replica fleet
# under open-loop load survives an injected engine crash mid-spike-burst
# and a wedged (hung, never erroring) replica — measured availability
# must be 1.0 with bitwise-identical predictions — then a hot-reload
# cycle promotes good checkpoints behind per-replica drains while an
# injected validation fault and a NaN checkpoint are refused by name and
# a torn newest falls back to the newest intact step; the whole trace is
# gated by `check_telemetry --require serve.fleet.,serve.reload.`
# (known event names, outstanding_at_swap == 0 on every swap).
serve-chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/serve_chaos_smoke.py

# Elastic smoke (docs/ROBUSTNESS.md §Elastic training): SIGKILL one rank
# of a seeded 2-process `--elastic` run; the survivor must
# rescue-checkpoint, re-wire into the world-1 membership under the next
# world generation, and finish the run — then the world grows back to 2
# with `--resume --reshape`, with loss-curve continuity asserted across
# the whole cycle and the post-reshape collective schedule proven by
# `trace report --cluster`, gated by `check_telemetry --require
# elastic.,cluster.`. On a jaxlib without CPU multiprocess collectives
# it degrades to the world-1 matrix (script exit 75 = the multiproc-skip
# signal): reshape math, a kill/resume-with-reshape cycle, and a forged
# 2-device manifest re-mapped down to 1.
elastic-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/elastic_smoke.py || \
		{ rc=$$?; [ $$rc -eq 75 ] && \
		JAX_PLATFORMS=cpu $(PY) scripts/elastic_smoke.py --world 1; }

# Static-analysis smoke (docs/STATIC_ANALYSIS.md): the source lint over
# the whole package (zero unbaselined findings or exit 1) plus the
# program auditor over the full comm x overlap x {step, run} matrix
# (exit 3 names the broken contract). Both are CPU-cheap: the lint is
# pure stdlib ast, the audit traces jaxprs over a deviceless AbstractMesh
# (no compile, no devices).
lint:
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu lint

audit-program:
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu audit-program

static-smoke: lint audit-program

# Runtime-sanitizer smoke (docs/STATIC_ANALYSIS.md §Runtime sanitizers):
# the serve selftest and a 2-epoch train under no_host_sync (zero
# block_until_ready; fetches exactly 2/flush on serve, epoch-granular in
# training), event_loop_stall (no single serve-loop callback past the
# threshold — the PR 9 sort-per-request class), and lock_trace (no
# runtime lock-order cycles — LOCK002's runtime confirmation).
sanitize-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/sanitize_smoke.py

# Input-pipeline smoke (docs/DATA.md): a synthetic-source training run
# through the staged pipeline (decode workers + depth-K device prefetch)
# under no_host_sync (zero block_until_ready; the PR 10 epoch-granular
# fetch budget holds with workers live) + lock_trace (no acquisition-order
# cycles on the new worker locks), then the emitted trace is gated with
# check_telemetry --require data. and the data_wait attribution report.
input-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/input_smoke.py

# Program-forensics smoke (docs/OBSERVABILITY.md §Program forensics): the
# full cost harvest on 8 fake CPU devices — every comm x overlap step
# program (statics builders) + the serve bucket ladder compiled, their
# XLA cost/memory records emitted as a JSONL trace AND a COST artifact —
# then the trace is gated on the xla.* compile metrics and mem.* HBM
# watermark gauges being present plus the program_cost record contract,
# the forensics report renders, and the compile/HBM regression gate
# round-trips against itself (a harvest never regresses vs itself).
cost-smoke:
	rm -rf /tmp/pdmt_cost_smoke
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) -m pytorch_ddp_mnist_tpu trace cost \
		--telemetry /tmp/pdmt_cost_smoke \
		-o /tmp/pdmt_cost_smoke/COST.json
	$(PY) scripts/check_telemetry.py --require xla.,mem. \
		/tmp/pdmt_cost_smoke
	$(PY) -m pytorch_ddp_mnist_tpu trace report --cost \
		/tmp/pdmt_cost_smoke/COST.json
	$(PY) -m pytorch_ddp_mnist_tpu trace report --cost \
		/tmp/pdmt_cost_smoke/COST.json \
		--baseline /tmp/pdmt_cost_smoke/COST.json

# Dispatch-forensics smoke (docs/OBSERVABILITY.md §Dispatch forensics): a
# profiled 2-epoch run (--profile_dispatch 4 samples the device-idle
# drain every 4th step), then the emitted dispatch records are schema-
# and contract-validated and gated on the dispatch.* histograms being
# present, the host-overhead decomposition report renders (with its
# >=90% phase-coverage assert), the phase-share regression gate
# round-trips against itself (a run never regresses vs itself), and the
# Perfetto export is checked to carry the host-dispatch and device-idle
# lanes.
overhead-smoke:
	rm -rf /tmp/pdmt_overhead_smoke
	JAX_PLATFORMS=cpu $(PY) -m pytorch_ddp_mnist_tpu train --epochs 2 \
		--limit 512 --batch_size 64 --checkpoint "" \
		--telemetry /tmp/pdmt_overhead_smoke --profile_dispatch 4
	$(PY) scripts/check_telemetry.py --require dispatch. \
		/tmp/pdmt_overhead_smoke
	$(PY) -m pytorch_ddp_mnist_tpu trace report --overhead \
		/tmp/pdmt_overhead_smoke
	$(PY) -m pytorch_ddp_mnist_tpu trace report --overhead --json \
		/tmp/pdmt_overhead_smoke > /tmp/pdmt_overhead_smoke/self.json
	$(PY) -m pytorch_ddp_mnist_tpu trace report --overhead \
		/tmp/pdmt_overhead_smoke \
		--baseline /tmp/pdmt_overhead_smoke/self.json
	$(PY) -m pytorch_ddp_mnist_tpu trace export /tmp/pdmt_overhead_smoke \
		-o /tmp/pdmt_overhead_smoke/trace.chrome.json
	$(PY) -c "import json; \
		d = json.load(open('/tmp/pdmt_overhead_smoke/trace.chrome.json')); \
		lanes = {e['args']['name'] for e in d['traceEvents'] \
			if e.get('ph') == 'M' and e.get('name') == 'thread_name'}; \
		assert {'host dispatch', 'device idle'} <= lanes, \
		'missing dispatch lanes: got %r' % sorted(lanes)"

# Performance-ledger smoke (docs/OBSERVABILITY.md §Performance ledger):
# the committed-artifact history must ingest (every schema generation),
# cover the six acceptance metric families in the trajectory report,
# self-gate exit 0, catch an injected direction-aware regression with
# exit 3 naming the series and the offending run (both the ledger CLI
# and the pairwise gates' --ledger mode), refuse unknown future
# schema_versions by name, and render one Perfetto counter track per
# series.
ledger-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/ledger_smoke.py

# The committed pre-merge gate: static contracts first (seconds), then the
# runtime sanitizers on the live paths (incl. the input pipeline), then
# the serve request-tracing round trip (also seconds), then the program
# cost/memory harvest round trip, then the dispatch-forensics round trip
# (host overhead decomposition + phase-share gate), then the
# cluster-forensics round trip (collective journal + hang attribution),
# then the performance-ledger round trip (the multi-run trend gate over
# the committed artifact history), then the fast test tier.
check: static-smoke sanitize-smoke input-smoke serve-trace-smoke serve-fast-smoke cost-smoke overhead-smoke cluster-smoke elastic-smoke ledger-smoke serve-chaos-smoke test-fast

# Live-health smoke (docs/OBSERVABILITY.md §Live health): inject
# nan:step=K into a short CPU run under --health checkpoint-and-warn and
# assert the full round trip — a fatal `nan` health event in the trace
# (check_telemetry --require health.), an INTACT finite checkpoint at a
# pre-NaN step (the rescue save), and a mid-run Prometheus /metrics
# scrape answering the registry + health_* gauges.
health-smoke:
	JAX_PLATFORMS=cpu $(PY) scripts/health_smoke.py

native:
	$(MAKE) -C pytorch_ddp_mnist_tpu/data/native

convert:
	$(PY) -m pytorch_ddp_mnist_tpu.data.convert --synthetic 60000:10000 --out_dir data/

clean:
	rm -f pytorch_ddp_mnist_tpu/data/native/_reader.so
	find . -name __pycache__ -type d -exec rm -rf {} +
